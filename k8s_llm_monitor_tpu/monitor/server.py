"""The HTTP JSON API server.

Parity target: ``/root/reference/cmd/server/main.go`` — the 14 registered
routes (:97-141) with the exact response envelopes of the handlers
(:175-695), including the nil-tolerant "development mode" degradation
(:196-204, :330-333), per-handler method checks, and the CORS header on
metrics routes (:328). Plus the endpoint the reference documents but never
registered: ``POST /api/v1/query`` (README.md:89-95), backed by the
Analysis Engine, and its typed sibling ``POST /api/v1/analyze``.

Stdlib ``ThreadingHTTPServer`` — no web framework needed; request
concurrency is thread-per-connection, with the inference engine doing its
own continuous batching underneath.
"""

from __future__ import annotations

import json
import logging
import math
import mimetypes
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Any
from urllib.parse import parse_qs, urlparse

from k8s_llm_monitor_tpu.monitor.analysis import AnalysisEngine
from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import ClusterError, NotFound
from k8s_llm_monitor_tpu.monitor.config import Config
from k8s_llm_monitor_tpu.monitor.manager import Manager
from k8s_llm_monitor_tpu.monitor.models import (
    AnalysisRequest,
    UAVReport,
    parse_rfc3339,
    rfc3339,
    to_jsonable,
    utcnow,
)
from k8s_llm_monitor_tpu.monitor.network import NetworkAnalyzer
from k8s_llm_monitor_tpu.observability.tracing import (
    get_tracer,
    parse_traceparent,
)
from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
from k8s_llm_monitor_tpu.resilience.slo import normalize_slo_class
from k8s_llm_monitor_tpu.resilience.tenancy import normalize_tenant
from k8s_llm_monitor_tpu.serving.kv_tier import BlobError

logger = logging.getLogger("monitor.server")

VERSION = "1.0.0"
DEFAULT_WEB_DIR = Path(__file__).resolve().parents[2] / "web"


def _now() -> str:
    return rfc3339(utcnow())


class MonitorServer:
    """Owns the HTTP server + the wired components.

    Every component is optional (dev mode): handlers degrade exactly like
    the reference when ``client`` / ``manager`` / ``analysis`` is None.
    """

    def __init__(
        self,
        config: Config | None = None,
        client: Client | None = None,
        manager: Manager | None = None,
        analysis: AnalysisEngine | None = None,
        web_dir: str | Path | None = None,
        host: str | None = None,
        port: int | None = None,
        diagnosis=None,
        signals=None,
    ) -> None:
        self.config = config or Config()
        self.client = client
        self.manager = manager
        self.analysis = analysis
        # diagnosis.pipeline.DiagnosisPipeline — the standing watcher→LLM
        # loop behind GET /api/v1/diagnoses and the diagnosis_* gauges.
        # None on routers (they proxy) and in dev mode.
        self.diagnosis = diagnosis
        # observability.signals.SignalScraper — the telemetry plane
        # behind GET /api/v1/signals + /api/v1/timeseries; shares the
        # server lifecycle (start/stop with the HTTP thread).  None in
        # dev mode or when telemetry.enabled=false.
        self.signals = signals
        self.web_dir = Path(web_dir) if web_dir else DEFAULT_WEB_DIR
        self.host = host if host is not None else self.config.server.host
        self.port = port if port is not None else self.config.server.port
        # Membership lifecycle: flipped by graceful shutdown (or an
        # operator) so /api/v1/stats announces draining one probe before
        # the process leaves — the router stops dispatching here while
        # in-flight streams finish.
        self.draining = False
        # fleet.autoscaler.AutoscaleController on router-role processes
        # with autoscale.enabled; wired by frontend.build_router_server.
        self.autoscaler = None
        # remediation.executor.RemediationEngine: the diagnosis pipeline's
        # plan stage, wired by build_server behind RemediationConfig.
        # None in dev mode (no cluster backend) or remediation.enabled=
        # false.  Read by /api/v1/remediations, /api/v1/stats, and the
        # exporter's remediation_* families.
        self.remediation = None
        # resilience.tenancy.TenantGovernor: per-tenant admission quotas.
        # Wired by build_server (single-replica: the backend's governor)
        # or build_router_server (fleet: the router's); None in dev mode
        # or with tenancy.enabled=false.  Read by /api/v1/stats and the
        # exporter's tenant_* families.
        self.governor = None
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- health ----------------------------------------------------------------

    def engine_service(self):
        """The wired EngineService, when a local engine backend is up."""
        backend = getattr(self.analysis, "backend", None)
        return getattr(backend, "service", None)

    def engine_supervisor(self):
        """The EngineSupervisor, when the backend runs in supervised mode."""
        backend = getattr(self.analysis, "backend", None)
        return getattr(backend, "supervisor", None)

    def request_shutdown(self) -> None:
        """Unblock ``serve_forever`` from another thread (signal handlers
        must not call ``httpd.shutdown`` from the serving thread itself —
        it would deadlock)."""
        httpd = self._httpd
        if httpd is not None:
            httpd.shutdown()

    def health_snapshot(self) -> dict[str, Any]:
        """Aggregate live health across the wired components — the body of
        ``/health``.  Dev mode (no engine) is healthy by definition: there
        is nothing to degrade."""
        snap: dict[str, Any] = {
            "status": "healthy",
            "reason": "",
            "ready": True,
            "timestamp": _now(),
            "version": VERSION,
        }
        svc = self.engine_service()
        if svc is not None:
            h = svc.health.snapshot()
            engine = svc.engine
            snap["status"] = h["state"]
            snap["reason"] = h["reason"]
            snap["ready"] = h["ready"]
            snap["engine"] = {
                "queue_depth": engine.queue_depth,
                "active_slots": engine.active_slots,
                "sheds": h["totals"]["sheds"],
                "recent_shed_rate": h["recent"]["shed_rate"],
                "watchdog_trips": engine.watchdog_trips,
                "dispatch_failures": engine.dispatch_failures,
                "consecutive_dispatch_failures":
                    engine.consecutive_dispatch_failures,
                "deadline_expired": engine.deadline_expired,
                "requeues": engine.requeues,
            }
        sup = self.engine_supervisor()
        if sup is not None:
            lc = sup.snapshot()
            snap["lifecycle"] = lc
            # A terminating/rebuilding/failed supervisor must stop traffic
            # even if the engine health state hasn't caught up yet.
            if lc["state"] != "serving":
                snap["ready"] = False
                if not snap["reason"]:
                    snap["reason"] = f"lifecycle state {lc['state']}"
        breaker = getattr(getattr(self.client, "backend", None),
                          "breaker", None)
        if breaker is not None:
            snap["kube_breaker"] = {
                "state": breaker.state,
                "trips": breaker.trips,
                "rejections": breaker.rejections,
            }
        router = self.fleet_router()
        if router is not None:
            replicas = router.registry.snapshot()
            snap["fleet"] = {
                "replicas": replicas,
                "counters": router.counters(),
            }
            # A router with zero ready replicas serves nothing: not ready.
            if not any(r["ready"] for r in replicas.values()):
                snap["ready"] = False
                snap["status"] = "degraded"
                if not snap["reason"]:
                    snap["reason"] = "no ready fleet replicas"
        return snap

    def fleet_router(self):
        """The FleetRouter, when this process runs the router role."""
        return getattr(self.analysis, "router", None)

    def stats_snapshot(self) -> dict[str, Any]:
        """Load-signal snapshot — the body of ``GET /api/v1/stats``.  The
        ``engine`` block is the fleet router's per-replica probe payload
        (queue backlog, slot occupancy, prefix-cache hit counters); the
        ``fleet`` block appears on router-role processes."""
        snap: dict[str, Any] = {
            "engine": None,
            "fleet": None,
            "timestamp": _now(),
        }
        svc = self.engine_service()
        if svc is not None:
            engine = svc.engine
            pc = engine.prefix_cache
            snap["engine"] = {
                "queue_depth": engine.queue_depth,
                "queue_tokens": engine.queue_tokens,
                "queue_tokens_by_class": engine.queue_tokens_by_class(),
                "brownout": (engine.brownout()
                             if engine.brownout is not None else 0),
                "busy_slots": engine.active_slots,
                "total_slots": engine.ecfg.max_slots,
                "prefix_deferrals": engine.prefix_deferrals,
                "prefix_cache": {
                    "hits": pc.hits,
                    "misses": pc.misses,
                    "evictions": pc.evictions,
                    "entries": len(pc),
                } if pc is not None else None,
                "kv_tier": engine.kv_tier_stats(),
                # Signal-scraper inputs (previously exporter-only): the
                # fleet probes and the telemetry plane read one coherent
                # snapshot instead of a second /metrics parse.
                "admission_headroom_tokens":
                    engine.admission_headroom_tokens(),
                "shed_by_class": dict(svc.shed_count_by_class),
                "ttft_ema_by_class": {
                    k: round(v, 6)
                    for k, v in engine.ttft_ema_by_class.items()},
                "preemptions_by_class": dict(engine.preemptions_by_class),
                # Disaggregation: the fleet probe reads this replica's
                # role + drain announcement from the same snapshot.
                "role": self.config.fleet.role,
                "draining": bool(self.draining),
            }
        router = self.fleet_router()
        if router is not None:
            snap["fleet"] = {
                "replicas": router.registry.snapshot(),
                "counters": router.counters(),
                "hedge_delay_s": round(router.hedge_delay_s(), 4),
            }
            if self.autoscaler is not None:
                snap["fleet"]["autoscaler"] = self.autoscaler.snapshot()
        if self.governor is not None:
            # Per-tenant accounting: admissions, quota refusals, sheds,
            # charged (delivered) tokens, in-flight reservations, and the
            # remaining token quota (-1 = unlimited).
            snap["tenants"] = self.governor.snapshot()
        if self.remediation is not None:
            snap["remediation"] = self.remediation.snapshot()
        return snap

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="monitor-http", daemon=True
        )
        self._thread.start()
        if self.diagnosis is not None:
            self.diagnosis.start()
        if self.signals is not None:
            self.signals.start()
        logger.info("monitor server listening on %s:%d", self.host, self.port)

    def stop(self) -> None:
        if self.signals is not None:
            self.signals.stop()
        if self.diagnosis is not None:
            self.diagnosis.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def serve_forever(self) -> None:
        if self.diagnosis is not None:
            self.diagnosis.start()
        if self.signals is not None:
            self.signals.start()
        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self._httpd.server_address[1]
        logger.info("monitor server listening on %s:%d", self.host, self.port)
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            logger.info("shutting down server...")
            self._httpd.server_close()


# method-name route table, static across requests (bound per request via
# getattr because handler instances are created per connection)
_ROUTES: dict[tuple[str, str], str] = {
    ("GET", "/health"): "h_health",
    ("GET", "/readyz"): "h_readyz",
    ("GET", "/api/v1/stats"): "h_stats",
    ("GET", "/metrics"): "h_prometheus",
    ("POST", "/debug/profile"): "h_profile",
    ("GET", "/api/v1/cluster/status"): "h_cluster_status",
    ("GET", "/api/v1/pods"): "h_pods",
    ("POST", "/api/v1/analyze/pod-communication"): "h_pod_comm",
    ("POST", "/api/v1/analyze"): "h_analyze",
    ("POST", "/api/v1/query"): "h_query",
    ("GET", "/api/v1/diagnoses"): "h_diagnoses",
    ("GET", "/api/v1/remediations"): "h_remediations",
    ("GET", "/api/v1/signals"): "h_signals",
    ("GET", "/api/v1/timeseries"): "h_timeseries",
    ("GET", "/api/v1/trace"): "h_trace_recent",
    ("GET", "/api/v1/metrics/cluster"): "h_metrics_cluster",
    ("GET", "/api/v1/metrics/nodes"): "h_metrics_nodes",
    ("GET", "/api/v1/metrics/pods"): "h_metrics_pods",
    ("GET", "/api/v1/metrics/snapshot"): "h_metrics_snapshot",
    ("GET", "/api/v1/metrics/network"): "h_metrics_network",
    ("GET", "/api/v1/metrics/uav"): "h_metrics_uav",
    ("POST", "/api/v1/uav/report"): "h_uav_report",
    ("POST", "/api/v1/uav/command"): "h_uav_command",
    ("GET", "/api/v1/crd/uav"): "h_uav_crd",
    ("POST", "/api/v1/kv/prefix"): "h_kv_prefix",
    ("POST", "/api/v1/kv/install"): "h_kv_install",
}
_ROUTE_PATHS = {p for _, p in _ROUTES}


def _make_handler(srv: MonitorServer) -> type[BaseHTTPRequestHandler]:
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # quiet default logging; route through our logger at debug
        def log_message(self, fmt: str, *args: Any) -> None:
            logger.debug("%s %s", self.address_string(), fmt % args)

        # -- plumbing ---------------------------------------------------------

        def _send_json(
            self, payload: Any, status: int = 200, cors: bool = False,
            headers: dict[str, str] | None = None,
        ) -> None:
            body = json.dumps(to_jsonable(payload)).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            if cors:
                self.send_header("Access-Control-Allow-Origin", "*")
            for name, value in (headers or {}).items():
                self.send_header(name, value)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_overloaded(self, exc: OverloadedError) -> None:
            retry_after = max(1, math.ceil(exc.retry_after_s))
            self._send_json(
                {
                    "status": "error",
                    "error": str(exc),
                    "error_kind": "overloaded",
                    "reason": exc.reason,
                    "retriable": exc.retriable,
                    "retry_after_s": exc.retry_after_s,
                    "queue_depth": exc.queue_depth,
                    "queue_tokens": exc.queue_tokens,
                    "slo_class": exc.slo_class,
                    # Tenant-tagged refusals: a quota 429 names the tenant
                    # it throttled, so client-side balancers back off the
                    # right traffic class (empty for untenanted refusals).
                    "tenant": exc.tenant,
                    # Assigned before the refusal: lets clients join the
                    # 429/503 with traces, logs, and the journal.
                    "request_id": exc.request_id,
                    "timestamp": _now(),
                },
                status=429 if exc.retriable else 503,
                headers={"Retry-After": str(retry_after)},
            )

        def _send_error_text(self, msg: str, status: int) -> None:
            # mirrors Go http.Error: plain text + newline
            body = (msg + "\n").encode()
            self.send_response(status)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _parse_tenant(self, body: dict[str, Any] | None = None) -> str:
            """Tenant identity at the trust boundary: the ``X-Tenant-Id``
            header wins over the body's ``"tenant"`` key; absent both,
            the default tenant.  Malformed ids raise ValueError — callers
            map it to a 400 before any engine work happens."""
            raw = (self.headers.get("X-Tenant-Id")
                   or (body or {}).get("tenant") or "")
            return normalize_tenant(raw)

        def _read_json(self) -> dict[str, Any]:
            """Parse the body as a JSON object; raises ValueError (which
            json.JSONDecodeError subclasses) for non-JSON and for valid JSON
            that isn't an object — both are the caller's fault (400)."""
            length = int(self.headers.get("Content-Length", 0) or 0)
            raw = self.rfile.read(length) if length else b""
            if not raw:
                return {}
            data = json.loads(raw)
            if not isinstance(data, dict):
                raise ValueError("JSON body must be an object")
            return data

        # -- routing ----------------------------------------------------------

        def do_GET(self) -> None:  # noqa: N802 (stdlib API)
            self._route("GET")

        def do_POST(self) -> None:  # noqa: N802
            self._route("POST")

        def _route(self, method: str) -> None:
            parsed = urlparse(self.path)
            path = parsed.path
            try:
                # Incoming W3C traceparent joins this handler (and every
                # downstream engine/replica call it makes) to the caller's
                # trace.  Requests without one are not traced at the HTTP
                # layer — generation paths start their own trace at
                # admission, and probe/static traffic stays out of the
                # ring.  A malformed header never fails the request.
                parent = parse_traceparent(
                    self.headers.get("traceparent") or "")
                if parent is not None:
                    with get_tracer().span(
                            "http.server", parent=parent,
                            attrs={"method": method, "path": path}):
                        return self._dispatch(method, path)
                return self._dispatch(method, path)
            except BrokenPipeError:
                pass
            except OverloadedError as exc:
                # Admission-control pushback from the engine/supervisor:
                # 429 when retrying this replica can work (shed, rebuild in
                # progress), 503 when it cannot (draining, failed).  Both
                # carry a Retry-After derived from the shed/restart backoff
                # and the queue evidence a client-side balancer needs.
                try:
                    self._send_overloaded(exc)
                except Exception:  # noqa: BLE001
                    pass
            except Exception as exc:  # noqa: BLE001 — server must not die
                logger.exception("handler error for %s %s", method, path)
                try:
                    self._send_error_text(f"Internal server error: {exc}", 500)
                except Exception:  # noqa: BLE001
                    pass

        def _dispatch(self, method: str, path: str) -> None:
            handler_name = _ROUTES.get((method, path))
            if handler_name is not None:
                return getattr(self, handler_name)()
            # prefix routes with a path parameter
            if path.startswith("/api/v1/metrics/nodes/"):
                if method != "GET":
                    return self._send_error_text("Method not allowed", 405)
                return self.h_metrics_node(path[len("/api/v1/metrics/nodes/") :])
            if path.startswith("/api/v1/metrics/uav/"):
                if method != "GET":
                    return self._send_error_text("Method not allowed", 405)
                return self.h_metrics_uav_node(path[len("/api/v1/metrics/uav/") :])
            if path.startswith("/api/v1/trace/"):
                if method != "GET":
                    return self._send_error_text("Method not allowed", 405)
                return self.h_trace(path[len("/api/v1/trace/") :])
            if path.startswith("/api/v1/remediations/"):
                if method != "POST":
                    return self._send_error_text("Method not allowed", 405)
                return self.h_remediation_action(
                    path[len("/api/v1/remediations/") :])
            if path in _ROUTE_PATHS:
                # registered path, wrong method (ref per-handler checks)
                return self._send_error_text("Method not allowed", 405)
            if method == "GET":
                return self.h_static(path)
            return self._send_error_text("404 page not found", 404)

        # -- static web (ref cmd/server/main.go:101) ---------------------------

        def h_static(self, path: str) -> None:
            rel = path.lstrip("/") or "index.html"
            base = srv.web_dir.resolve()
            target = (base / rel).resolve()
            if not target.is_relative_to(base) or not target.is_file():
                return self._send_error_text("404 page not found", 404)
            ctype = mimetypes.guess_type(str(target))[0] or "application/octet-stream"
            data = target.read_bytes()
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        # -- handlers ----------------------------------------------------------

        def h_health(self) -> None:
            # Real state, not a literal: DEGRADED still serves (200),
            # DRAINING/UNHEALTHY answer 503 so probes stop routing here.
            snap = srv.health_snapshot()
            self._send_json(snap, status=200 if snap["ready"] else 503)

        def h_readyz(self) -> None:
            """Readiness probe: should this replica receive traffic?"""
            snap = srv.health_snapshot()
            self._send_json(
                {
                    "ready": snap["ready"],
                    "status": snap["status"],
                    "reason": snap["reason"],
                    "timestamp": snap["timestamp"],
                },
                status=200 if snap["ready"] else 503,
            )

        def h_stats(self) -> None:
            """Load signal: engine queue/slot/prefix-cache counters (what
            the fleet router ranks replicas on), fleet state on routers."""
            self._send_json(srv.stats_snapshot())

        def h_prometheus(self) -> None:
            # Self-observability the reference never had (SURVEY §5.5):
            # engine/manager/device gauges in Prometheus text format.
            # OpenMetrics is Accept-negotiated: that mode adds exemplars
            # (trace ids on latency histogram buckets) and the EOF marker;
            # the default stays plain 0.0.4 text, exemplar-free.
            from k8s_llm_monitor_tpu.monitor.exporter import render_prometheus

            accept = self.headers.get("Accept") or ""
            openmetrics = "application/openmetrics-text" in accept
            body = render_prometheus(srv, openmetrics=openmetrics).encode()
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "application/openmetrics-text; version=1.0.0; charset=utf-8"
                if openmetrics else
                "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def h_trace_recent(self) -> None:
            """Most recent traces in the span ring (id, span count, root
            span name) plus tracer counters — the entry point for picking
            a trace id to fetch in full."""
            query = parse_qs(urlparse(self.path).query)
            try:
                limit = int((query.get("limit", ["20"])[0]) or 20)
            except ValueError:
                return self._send_error_text("limit must be an integer", 400)
            tracer = get_tracer()
            self._send_json({
                "status": "success",
                "traces": tracer.recent(limit),
                "sample_rate": tracer.sample,
                "spans_recorded": tracer.recorded,
                "timestamp": _now(),
            })

        def h_trace(self, ref: str) -> None:
            """One trace by request id or 32-hex trace id.  On router
            roles the local spans are merged with every registered
            replica's ring (dedup by span id, ordered by wall-clock
            start) so a hedged / failed-over request reads as ONE
            timeline across processes."""
            ref = ref.strip().rstrip("/")
            if not ref:
                return self._send_error_text(
                    "trace or request id is required", 400)
            tracer = get_tracer()
            trace_id = tracer.lookup(ref)
            if trace_id is None:
                return self._send_error_text(
                    f"unknown trace or request id: {ref}", 404)
            spans = tracer.spans_for(trace_id)
            sources = ["local"]
            router = srv.fleet_router()
            if router is not None:
                seen = {s["span_id"] for s in spans}
                for rid, replica in router.replicas():
                    try:
                        remote = replica.fetch_trace(trace_id)
                    except Exception:  # noqa: BLE001 — merge best-effort
                        continue
                    fresh = [s for s in remote
                             if s.get("span_id") not in seen]
                    if fresh:
                        seen.update(s["span_id"] for s in fresh)
                        spans.extend(fresh)
                        sources.append(rid)
                spans.sort(key=lambda s: s.get("start_unix", 0.0))
            self._send_json({
                "status": "success",
                "trace_id": trace_id,
                "spans": spans,
                "n_spans": len(spans),
                "sources": sources,
                "timestamp": _now(),
            })

        def h_profile(self) -> None:
            """Capture a jax.profiler trace (debug mode only): body
            {"seconds": N, "dir": path?} -> {"trace_dir": ...}."""
            if not srv.config.server.debug:
                return self._send_error_text(
                    "profiling requires server.debug=true", 403)
            try:
                body = self._read_json() or {}
            except ValueError:
                return self._send_error_text("Invalid JSON body", 400)
            seconds = min(float(body.get("seconds", 2.0)), 60.0)
            # "dir" is a subdirectory NAME under the trace root, never an
            # arbitrary filesystem path (debug-gated but unauthenticated —
            # advisor r3).
            root = "/tmp/k8s-llm-monitor-trace"
            sub = str(body.get("dir") or "")
            if sub and (sub != os.path.basename(sub) or sub.startswith(".")):
                return self._send_error_text(
                    "dir must be a plain subdirectory name", 400)
            trace_dir = os.path.join(root, sub) if sub else root
            import time as _time

            import jax

            jax.profiler.start_trace(trace_dir)
            _time.sleep(seconds)
            jax.profiler.stop_trace()
            payload: dict[str, Any] = {
                "trace_dir": trace_dir, "seconds": seconds}
            if body.get("decode_phases"):
                # Refresh the per-phase decode cost split (and the
                # engine_decode_* gauges + collective_share span
                # attribute that ride on it) behind the same debug gate.
                # Requires an idle engine; refusal is reported, not fatal.
                try:
                    payload["decode_phases"] = self._engine_call(
                        lambda e: e.profile_decode_phases())
                except LookupError:
                    payload["decode_phases_error"] = "no local engine"
                except Exception as exc:  # noqa: BLE001 — busy engine
                    payload["decode_phases_error"] = str(exc)
            self._send_json(payload)

        def h_cluster_status(self) -> None:
            if srv.client is None:
                return self._send_json(
                    {
                        "status": "warning",
                        "message": "K8s client not available - running in development mode",
                        "timestamp": _now(),
                    }
                )
            try:
                info = srv.client.get_cluster_info()
            except ClusterError as exc:
                return self._send_error_text(
                    f"Failed to get cluster info: {exc}", 500
                )
            self._send_json(
                {"status": "success", "cluster_info": info, "timestamp": _now()}
            )

        def h_pods(self) -> None:
            if srv.client is None:
                return self._send_json(
                    {
                        "status": "warning",
                        "message": "K8s client not available - running in development mode",
                        "pods": [],
                        "timestamp": _now(),
                    }
                )
            all_pods = []
            for ns in srv.client.namespaces():
                try:
                    all_pods.extend(srv.client.get_pods(ns))
                except ClusterError as exc:
                    logger.warning("failed to get pods from %s: %s", ns, exc)
            self._send_json(
                {
                    "status": "success",
                    "pods": all_pods,
                    "count": len(all_pods),
                    "timestamp": _now(),
                }
            )

        def h_pod_comm(self) -> None:
            if srv.client is None:
                return self._send_error_text(
                    "K8s client not available - running in development mode", 503
                )
            try:
                body = self._read_json() or {}
            except ValueError:
                return self._send_error_text("Invalid JSON body", 400)
            pod_a, pod_b = body.get("pod_a", ""), body.get("pod_b", "")
            if not pod_a or not pod_b:
                return self._send_error_text("pod_a and pod_b are required", 400)
            try:
                # LLM-augmented when the Analysis Engine is wired; plain
                # rule-based pipeline otherwise (reference behavior)
                if srv.analysis is not None:
                    resp = srv.analysis.analyze(
                        AnalysisRequest(
                            type="pod_communication",
                            parameters={"pod_a": pod_a, "pod_b": pod_b},
                        )
                    )
                    if resp.status != "success":
                        return self._send_error_text(
                            f"Analysis failed: {resp.error}", 500
                        )
                    payload = {
                        "status": "success",
                        "analysis": resp.result.get("analysis"),
                        "llm_diagnosis": resp.result.get("llm_diagnosis"),
                        "model": resp.result.get("model"),
                        "timestamp": _now(),
                    }
                    return self._send_json(payload)
                analysis = NetworkAnalyzer(srv.client).analyze_pod_communication(
                    pod_a, pod_b
                )
            except NotFound as exc:
                return self._send_error_text(f"Analysis failed: {exc}", 500)
            except ClusterError as exc:
                return self._send_error_text(f"Analysis failed: {exc}", 500)
            self._send_json(
                {"status": "success", "analysis": analysis, "timestamp": _now()}
            )

        def h_query(self) -> None:
            if srv.analysis is None:
                return self._send_error_text(
                    "Analysis engine not available - running in development mode",
                    503,
                )
            try:
                body = self._read_json() or {}
            except ValueError:
                return self._send_error_text("Invalid JSON body", 400)
            question = (body.get("question") or "").strip()
            if not question:
                return self._send_error_text("question is required", 400)
            try:
                # Operator-facing queries default to the interactive lane;
                # callers may opt down to "standard" or "batch".
                slo_class = normalize_slo_class(
                    str(body.get("slo_class") or ""), default="interactive")
                tenant = self._parse_tenant(body)
            except ValueError as exc:
                return self._send_error_text(str(exc), 400)
            if body.get("stream"):
                return self._stream_query(question, slo_class, tenant)
            # Multi-turn follow-ups: "session_id" (even "", which mints a
            # new session) pins the conversation to one frozen cluster
            # context whose token prefix replays every turn — PrefixCache
            # hits locally, prefix-affinity in fleet mode.
            if "session_id" in body:
                if not hasattr(srv.analysis, "query_session"):
                    return self._send_error_text(
                        "sessions are not supported on this role", 400)
                resp = srv.analysis.query_session(
                    question, str(body.get("session_id") or ""),
                    slo_class=slo_class, tenant=tenant)
            else:
                resp = srv.analysis.query(question, slo_class=slo_class,
                                          tenant=tenant)
            self._send_json(resp, status=200 if resp.status == "success" else 500)

        def h_diagnoses(self) -> None:
            """Verdict history from the standing diagnosis pipeline; on
            router roles this proxies to a replica (FleetAnalysis)."""
            query = parse_qs(urlparse(self.path).query)
            try:
                limit = int((query.get("limit", ["0"])[0]) or 0)
            except ValueError:
                return self._send_error_text("limit must be an integer", 400)
            pipe = srv.diagnosis
            if pipe is not None:
                return self._send_json({
                    "status": "success",
                    "diagnoses": pipe.store.snapshot(limit),
                    "count": len(pipe.store),
                    "verdicts_total": pipe.store.counts(),
                    "pipeline": {
                        "triggers": pipe.triggers_total,
                        "queries": pipe.queries_total,
                        "errors": pipe.errors_total,
                        "lag_ms": pipe.store.lag_ms(),
                        "pending_events": pipe.detector.pending(),
                        "context_events": len(pipe.context),
                    },
                    "timestamp": _now(),
                })
            proxy = getattr(srv.analysis, "diagnoses", None)
            if callable(proxy):
                try:
                    return self._send_json(proxy(limit))
                except OverloadedError:
                    raise
                except Exception as exc:  # noqa: BLE001 — fleet edge
                    return self._send_error_text(
                        f"diagnoses unavailable: {exc}", 502)
            return self._send_error_text(
                "Diagnosis pipeline not available - running in development "
                "mode", 503)

        def h_remediations(self) -> None:
            """Stored action plans from the remediation engine, newest
            first, plus the outcome counters the exporter renders."""
            rem = srv.remediation
            if rem is None:
                return self._send_error_text(
                    "Remediation engine not available - running without a "
                    "cluster backend or remediation.enabled=false", 503)
            query = parse_qs(urlparse(self.path).query)
            try:
                limit = int((query.get("limit", ["0"])[0]) or 0)
            except ValueError:
                return self._send_error_text("limit must be an integer", 400)
            self._send_json({
                "status": "success",
                "remediations": rem.records(limit),
                "counters": rem.snapshot(),
                "timestamp": _now(),
            })

        def h_remediation_action(self, rest: str) -> None:
            """Per-plan approval path: ``<id>/approve`` executes the plan
            (the operator saying "do it" — this clears the destructive-verb
            gate for that one plan, even in observe-only mode);
            ``<id>/reject`` parks it."""
            rem = srv.remediation
            if rem is None:
                return self._send_error_text(
                    "Remediation engine not available", 503)
            rec_id, _, action = rest.partition("/")
            if action not in ("approve", "reject") or not rec_id:
                return self._send_error_text(
                    "use /api/v1/remediations/<id>/approve or .../reject",
                    404)
            rec = (rem.approve(rec_id) if action == "approve"
                   else rem.reject(rec_id))
            if rec is None:
                return self._send_error_text(
                    f"remediation {rec_id} not found", 404)
            self._send_json({
                "status": "success",
                "action": action,
                "remediation": rec,
                "timestamp": _now(),
            })

        def h_signals(self) -> None:
            """Derived autoscaler/anomaly signals from the telemetry
            plane: fleet-merged per-replica blocks on routers, the local
            engine block on replicas.  ``?window=N`` overrides the
            trailing window (seconds)."""
            scraper = srv.signals
            if scraper is None:
                return self._send_error_text(
                    "Signal scraper not available - running in "
                    "development mode", 503)
            query = parse_qs(urlparse(self.path).query)
            window = None
            raw = (query.get("window", [""])[0] or "").strip()
            if raw:
                try:
                    window = float(raw)
                except ValueError:
                    return self._send_error_text(
                        "window must be a number of seconds", 400)
                if window <= 0:
                    return self._send_error_text(
                        "window must be positive", 400)
            payload = scraper.signals(window_s=window)
            payload["status"] = "success"
            payload["timestamp"] = _now()
            self._send_json(payload)

        def h_timeseries(self) -> None:
            """Raw points of one series family for dashboards:
            ``?name=<series>&window=N`` plus any further query params as
            label equality filters (e.g. ``&replica=replica-0``)."""
            scraper = srv.signals
            if scraper is None:
                return self._send_error_text(
                    "Signal scraper not available - running in "
                    "development mode", 503)
            query = parse_qs(urlparse(self.path).query)
            name = (query.get("name", [""])[0] or "").strip()
            if not name:
                return self._send_error_text("name is required", 400)
            window = scraper.cfg.window_s
            raw = (query.get("window", [""])[0] or "").strip()
            if raw:
                try:
                    window = float(raw)
                except ValueError:
                    return self._send_error_text(
                        "window must be a number of seconds", 400)
            labels = {k: v[0] for k, v in query.items()
                      if k not in ("name", "window") and v}
            series = scraper.store.export(
                name, window_s=window, label_filter=labels or None)
            self._send_json({
                "status": "success",
                "name": name,
                "window_s": window,
                "series": series,
                "n_series": len(series),
                "timestamp": _now(),
            })

        def _stream_query(self, question: str,
                          slo_class: str = "interactive",
                          tenant: str = "") -> None:
            """Server-sent events: one `data:` JSON per answer-text delta as
            tokens come off the device, then a final done event.  TTFT is
            real for clients here — the first delta arrives while the rest
            of the answer is still decoding."""
            try:
                request_id, model, chunks = srv.analysis.query_stream(
                    question, slo_class=slo_class, tenant=tenant)
            except OverloadedError as exc:  # headers not sent yet: 429/503
                return self._send_overloaded(exc)
            except Exception as exc:  # noqa: BLE001 — before headers: 500
                return self._send_error_text(f"query failed: {exc}", 500)
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def event(payload: dict[str, Any]) -> None:
                data = f"data: {json.dumps(payload)}\n\n".encode()
                self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                self.wfile.flush()

            try:
                for chunk in chunks:
                    event({"request_id": request_id, "delta": chunk})
                event({"request_id": request_id, "done": True, "model": model})
            except BrokenPipeError:
                # Client went away mid-stream: close the generator so the
                # backend cancels the in-flight generation.
                if hasattr(chunks, "close"):
                    chunks.close()
                return
            except Exception as exc:  # noqa: BLE001 — headers already sent
                try:
                    event({"request_id": request_id, "error": str(exc)})
                except BrokenPipeError:
                    return
            try:
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()
            except BrokenPipeError:
                pass

        def h_analyze(self) -> None:
            if srv.analysis is None:
                return self._send_error_text(
                    "Analysis engine not available - running in development mode",
                    503,
                )
            try:
                body = self._read_json() or {}
            except ValueError:
                return self._send_error_text("Invalid JSON body", 400)
            try:
                tenant = self._parse_tenant(body)
            except ValueError as exc:
                return self._send_error_text(str(exc), 400)
            req = AnalysisRequest(
                type=body.get("type", ""),
                parameters=body.get("parameters") or {},
                context=body.get("context") or {},
            )
            resp = srv.analysis.analyze(req, tenant=tenant)
            if resp.status == "success":
                return self._send_json(resp)
            # validation errors are the caller's fault; everything else is a
            # server-side failure monitoring clients should retry on
            self._send_json(resp, status=400 if resp.error_kind == "validation" else 500)

        # -- KV prefix migration (serving/kv_tier.py blob framing) --------------

        def _engine_call(self, fn):
            """Run ``fn(engine)`` on the step thread via the supervisor's
            (preferred) or service's ``call`` seam; None when this role
            runs no local engine."""
            sup = srv.engine_supervisor()
            if sup is not None:
                return sup.call(fn)
            svc = srv.engine_service()
            if svc is None:
                raise LookupError("no local engine")
            return svc.call(fn)

        def h_kv_prefix(self) -> None:
            """Page-fetch endpoint: body ``{"token_ids": [...]}`` ->
            framed KV blob (octet-stream) for the longest cached prefix,
            or 404 on a cache miss.  The fleet router's migration path
            calls this on the prefix-affinity owner."""
            try:
                body = self._read_json() or {}
            except ValueError:
                return self._send_error_text("Invalid JSON body", 400)
            ids = body.get("token_ids")
            if (not isinstance(ids, list) or not ids
                    or not all(isinstance(t, int) for t in ids)):
                return self._send_error_text(
                    "token_ids must be a non-empty list of ints", 400)
            try:
                tenant = self._parse_tenant(body)
            except ValueError as exc:
                return self._send_error_text(str(exc), 400)
            try:
                blob = self._engine_call(
                    lambda e: e.export_prefix([int(t) for t in ids],
                                              tenant=tenant))
            except LookupError:
                return self._send_error_text(
                    "Engine not available - running in development mode",
                    503)
            if blob is None:
                return self._send_error_text("no cached prefix", 404)
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def h_kv_install(self) -> None:
            """Install a fetched prefix blob (raw octet-stream body) into
            the local KV pool; responds with the engine's outcome string
            (``installed``/``cached``/``incompatible``/``nospace``/
            ``tenant_mismatch``).  The body is the raw blob, so tenant
            identity rides only on the ``X-Tenant-Id`` header: when set,
            a blob packed under a different tenant's namespace is refused
            as ``tenant_mismatch``; absent, the blob's own header rules.
            Framing/CRC damage is the sender's fault: 400."""
            raw_tenant = self.headers.get("X-Tenant-Id") or ""
            try:
                expected = (normalize_tenant(raw_tenant)
                            if raw_tenant else None)
            except ValueError as exc:
                return self._send_error_text(str(exc), 400)
            length = int(self.headers.get("Content-Length", 0) or 0)
            blob = self.rfile.read(length) if length else b""
            if not blob:
                return self._send_error_text("empty blob", 400)
            try:
                outcome = self._engine_call(
                    lambda e: e.install_prefix(blob,
                                               expected_tenant=expected))
            except LookupError:
                return self._send_error_text(
                    "Engine not available - running in development mode",
                    503)
            except BlobError as exc:
                return self._send_error_text(f"bad blob: {exc}", 400)
            self._send_json({"status": "success", "outcome": outcome,
                             "timestamp": _now()})

        # -- metrics handlers (CORS like ref :328) ------------------------------

        def _need_manager(self) -> bool:
            if srv.manager is None:
                self._send_error_text("Metrics manager not available", 503)
                return False
            return True

        def h_metrics_cluster(self) -> None:
            if not self._need_manager():
                return
            self._send_json(
                {
                    "status": "success",
                    "data": srv.manager.get_cluster_metrics(),
                    "timestamp": _now(),
                },
                cors=True,
            )

        def h_metrics_nodes(self) -> None:
            if not self._need_manager():
                return
            snap = srv.manager.get_latest_snapshot()
            self._send_json(
                {
                    "status": "success",
                    "data": snap.node_metrics,
                    "count": len(snap.node_metrics),
                    "timestamp": rfc3339(snap.timestamp),
                },
                cors=True,
            )

        def h_metrics_node(self, node_name: str) -> None:
            if not self._need_manager():
                return
            if not node_name:
                return self._send_error_text("Node name is required", 400)
            try:
                node = srv.manager.get_node_metrics(node_name)
            except KeyError as exc:
                return self._send_error_text(f"Node not found: {exc}", 404)
            self._send_json(
                {"status": "success", "data": node, "timestamp": _now()}, cors=True
            )

        def h_metrics_pods(self) -> None:
            if not self._need_manager():
                return
            snap = srv.manager.get_latest_snapshot()
            self._send_json(
                {
                    "status": "success",
                    "data": snap.pod_metrics,
                    "count": len(snap.pod_metrics),
                    "timestamp": rfc3339(snap.timestamp),
                },
                cors=True,
            )

        def h_metrics_snapshot(self) -> None:
            if not self._need_manager():
                return
            self._send_json(
                {"status": "success", "data": srv.manager.get_latest_snapshot()},
                cors=True,
            )

        def h_metrics_network(self) -> None:
            if not self._need_manager():
                return
            nets = srv.manager.get_network_metrics()
            self._send_json(
                {
                    "status": "success",
                    "data": nets,
                    "count": len(nets),
                    "timestamp": _now(),
                },
                cors=True,
            )

        def h_metrics_uav(self) -> None:
            if not self._need_manager():
                return
            uavs = srv.manager.get_uav_metrics()
            self._send_json(
                {
                    "status": "success",
                    "data": uavs,
                    "count": len(uavs),
                    "timestamp": _now(),
                },
                cors=True,
            )

        def h_metrics_uav_node(self, node_name: str) -> None:
            if not self._need_manager():
                return
            if not node_name:
                return self._send_error_text("Node name is required", 400)
            entry = srv.manager.get_single_uav_metrics(node_name)
            if entry is None:
                return self._send_error_text(
                    f"UAV not found on node: {node_name}", 404
                )
            self._send_json(
                {"status": "success", "data": entry, "timestamp": _now()}, cors=True
            )

        # -- UAV report ingestion (ref :569-645) --------------------------------

        def h_uav_command(self) -> None:
            """Push a flight command to a node's UAV agent — the server-side
            surface the reference's SendCommandToUAV lacked (its payload
            marshaling was an unfinished TODO, ref uav_metrics.go:254-266,
            and no HTTP route ever called it)."""
            if srv.manager is None:
                return self._send_json(
                    {"status": "warning",
                     "message": "Metrics manager not available - running "
                                "in development mode"},
                    503,
                )
            try:
                body = self._read_json() or {}
            except ValueError:
                return self._send_error_text("Invalid JSON body", 400)
            node = body.get("node", "")
            command = body.get("command", "")
            if not node or not command:
                return self._send_error_text("node and command are required", 400)
            if command not in ("arm", "disarm", "takeoff", "land", "rtl", "mode"):
                return self._send_error_text(
                    f"unknown command {command!r}", 400)
            if srv.manager.uav_source is None:
                return self._send_error_text(
                    "UAV metrics source is disabled", 503)
            try:
                result = srv.manager.send_uav_command(
                    node, command, body.get("params") or {})
            except ValueError as exc:
                return self._send_error_text(str(exc), 404)
            except Exception as exc:  # noqa: BLE001 — agent unreachable
                return self._send_error_text(f"command failed: {exc}", 502)
            self._send_json({"status": "success", "node": node,
                             "command": command, "agent_response": result})

        def h_uav_report(self) -> None:
            try:
                body = self._read_json() or {}
            except ValueError:
                return self._send_error_text("Invalid JSON body", 400)
            node_name = body.get("node_name", "")
            if not node_name:
                return self._send_error_text("node_name is required", 400)
            try:
                heartbeat = int(body.get("heartbeat_interval_seconds", 0) or 0)
            except (TypeError, ValueError):
                return self._send_error_text(
                    "heartbeat_interval_seconds must be a number", 400
                )
            report = UAVReport(
                node_name=node_name,
                node_ip=body.get("node_ip", ""),
                uav_id=body.get("uav_id") or f"uav-{node_name}",
                source=body.get("source") or "agent",
                status=body.get("status") or "active",
                timestamp=parse_rfc3339(body.get("timestamp")) or utcnow(),
                heartbeat_interval_seconds=heartbeat,
                state=body.get("state"),
                metadata=body.get("metadata") or {},
            )
            if srv.manager is not None:
                srv.manager.update_uav_report(report)
            else:
                logger.warning(
                    "metrics manager unavailable, skipping cache update for %s",
                    node_name,
                )
            crd_status, crd_error = "unavailable", ""
            if srv.client is not None:
                try:
                    srv.client.upsert_uav_metric("", report)
                    crd_status = "updated"
                except (ClusterError, ValueError) as exc:
                    logger.warning("UAVMetric upsert failed for %s: %s", node_name, exc)
                    crd_status, crd_error = "error", str(exc)
            payload: dict[str, Any] = {
                "status": "success",
                "crd_status": crd_status,
                "timestamp": _now(),
                "node_name": report.node_name,
                "uav_id": report.uav_id,
                "uav_status": report.status,
            }
            if report.heartbeat_interval_seconds > 0:
                payload["heartbeat_interval_seconds"] = (
                    report.heartbeat_interval_seconds
                )
            if crd_error:
                payload["message"] = crd_error
            self._send_json(payload, cors=True)

        # -- UAV CRD listing (ref :648-695) -------------------------------------

        def h_uav_crd(self) -> None:
            if srv.client is None:
                return self._send_json(
                    {"status": "error", "message": "K8s client not available"},
                    status=503,
                    cors=True,
                )
            query = parse_qs(urlparse(self.path).query)
            namespace = (query.get("namespace", [""])[0] or "").strip()
            if namespace.lower() == "all":
                namespace = ""
            try:
                data = srv.client.list_uav_metrics_crd(namespace)
            except ClusterError as exc:
                logger.warning("failed to list UAV CRD data: %s", exc)
                return self._send_json(
                    {"status": "error", "message": str(exc)}, status=500, cors=True
                )
            self._send_json(
                {
                    "status": "success",
                    "count": len(data),
                    "data": data,
                    "timestamp": _now(),
                },
                cors=True,
            )

    return Handler


def build_server(
    config: Config,
    backend=None,
    uav_fetcher=None,
    web_dir: str | Path | None = None,
) -> MonitorServer:
    """Wire the full server from config: cluster backend → client → manager
    → analysis engine → HTTP. ``backend=None`` boots dev mode (no cluster),
    like the reference's nil-client path (cmd/server/main.go:43-51)."""
    from k8s_llm_monitor_tpu.diagnosis.session import SessionManager
    from k8s_llm_monitor_tpu.monitor.analysis import build_backend

    client = None
    manager = None
    if backend is not None:
        client = Client(
            backend,
            namespaces=config.k8s.watch_namespaces,
            default_namespace=config.k8s.namespace,
        )
        try:
            client.test_connection()
        except ClusterError as exc:
            logger.warning(
                "cluster unreachable (%s) - running in development mode", exc
            )
            client = None
    if client is not None and config.metrics.enabled:
        manager = Manager(client, config.metrics, uav_fetcher=uav_fetcher)
    llm_backend = build_backend(config.llm, lifecycle=config.lifecycle,
                                tenancy=config.tenancy)
    detector = None
    if config.analysis.embedding_model:
        try:
            from k8s_llm_monitor_tpu.analysis.anomaly import (
                EmbeddingAnomalyDetector,
            )
            from k8s_llm_monitor_tpu.models.config import ENCODER_PRESETS

            name = config.analysis.embedding_model
            if name in ENCODER_PRESETS:
                detector = EmbeddingAnomalyDetector(ENCODER_PRESETS[name])
            else:
                detector = EmbeddingAnomalyDetector.from_checkpoint(name)
        except Exception as exc:  # noqa: BLE001 — degrade, never fail boot
            logger.warning(
                "embedding detector unavailable (%s) - thresholds only", exc
            )
    analysis = AnalysisEngine(
        llm_backend,
        client=client,
        manager=manager,
        cfg=config.analysis,
        llm_cfg=config.llm,
        anomaly_detector=detector,
    )
    analysis.sessions = SessionManager(
        ttl_s=config.diagnosis.session_ttl_s,
        max_sessions=config.diagnosis.max_sessions,
    )
    diagnosis = None
    if config.diagnosis.enabled:
        # The pipeline is constructed here but its worker thread starts
        # with the HTTP server (start()/serve_forever()); the Watcher
        # feeding it is wired by cmd/server.py, which owns thread
        # lifecycles.  The embedding detector doubles as the retrieval
        # encoder for context assembly.
        from k8s_llm_monitor_tpu.diagnosis.pipeline import DiagnosisPipeline

        # Brownout coupling: at DRAINING the pipeline pauses new triggers
        # (the backend exposes the rung only when it runs a local engine).
        brownout = getattr(llm_backend, "brownout_level", None)
        diagnosis = DiagnosisPipeline(
            analysis, config.diagnosis, embedder=detector,
            brownout=brownout)
    signals = None
    if config.telemetry.enabled:
        from k8s_llm_monitor_tpu.observability.signals import SignalScraper

        # Anomaly flags feed the diagnosis pipeline's event ring as
        # synthetic self_monitor Warnings — the monitor diagnoses its
        # own serving stack.
        signals = SignalScraper(cfg=config.telemetry, pipeline=diagnosis)
    srv = MonitorServer(
        config=config,
        client=client,
        manager=manager,
        analysis=analysis,
        web_dir=web_dir,
        diagnosis=diagnosis,
        signals=signals,
    )
    # Single-replica tenancy: the backend's governor (None for remote/
    # template backends or tenancy.enabled=false) feeds /api/v1/stats
    # and the exporter's tenant_* families.
    srv.governor = getattr(llm_backend, "governor", None)
    # Closed-loop remediation: the pipeline's plan stage.  Needs both a
    # cluster backend (targets are enumerated from live state) and the
    # diagnosis pipeline (verdicts are the input); observe-only unless
    # config.remediation.execute or a per-plan approval says otherwise.
    if (config.remediation.enabled and backend is not None
            and diagnosis is not None):
        from k8s_llm_monitor_tpu.remediation.executor import (
            RemediationEngine,
        )

        remediation = RemediationEngine(
            backend, analysis, config.remediation,
            namespaces=tuple(config.k8s.watch_namespaces),
            pipeline=diagnosis,
        )
        diagnosis.remediation = remediation
        srv.remediation = remediation
    if signals is not None:
        signals.attach(srv)
        # Crash-edge dumps (flight recorder v2) carry the trailing
        # signal window: the load trajectory into the failure.
        from k8s_llm_monitor_tpu.observability.flight import (
            get_flight_recorder,
        )

        get_flight_recorder().signal_source = (
            lambda: signals.store.window_snapshot(
                config.telemetry.flight_window_s))
    return srv
