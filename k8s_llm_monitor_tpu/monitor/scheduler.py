"""UAV-aware scheduling controller over the CRD bus.

Parity target: ``/root/reference/internal/scheduler/controller.go`` —
poll-based reconcile (not informer-based) listing ``scheduler.io/v1
schedulingrequests`` and ``monitoring.io/v1 uavmetrics`` cluster-wide each
tick (:88-110), processing only empty/Pending requests (:112-120), manual
spec decoding + workload validation (:121-150), candidate building with
the battery filter + ``collection_status == "active"`` gate and the
battery + preferred-node-bonus scoring (:174-221), and status writes
through the ``/status`` subresource (:223-250).

Extension over the reference: candidates on nodes with TPU chips get a
configurable bonus so accelerator workloads land next to the inference
plane (the reference accepts but ignores such annotations — see
examples/multi-pod-request.yaml's comment).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Any

from k8s_llm_monitor_tpu.monitor.client import (
    SCHEDULING_GVR,
    UAV_METRICS_GVR,
    Client,
)
from k8s_llm_monitor_tpu.monitor.cluster import ClusterError
from k8s_llm_monitor_tpu.monitor.models import (
    SchedulingCandidate,
    parse_rfc3339,
    rfc3339,
    utcnow,
)

logger = logging.getLogger("monitor.scheduler")

PREFERRED_NODE_BONUS = 10.0  # ref controller.go:205-208


@dataclass
class SchedulerConfig:
    interval: float = 15.0  # ref cmd/scheduler/main.go:24 default
    tpu_node_bonus: float = 5.0  # extension: prefer TPU-carrying nodes
    # Staleness gate (fixes the reference's soft spot, controller.go:202-203:
    # heartbeat parsed but never used — a dead UAV with a fresh-looking CR
    # could win placement).  A candidate is excluded when its last_update is
    # older than ``stale_heartbeat_factor`` x its advertised heartbeat
    # interval, or older than ``stale_after_seconds`` when no interval is
    # advertised.  <= 0 disables either gate.
    stale_heartbeat_factor: float = 3.0
    stale_after_seconds: float = 120.0


class SchedulerController:
    def __init__(self, client: Client, cfg: SchedulerConfig | None = None) -> None:
        self.client = client
        self.cfg = cfg or SchedulerConfig()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.reconcile_count = 0
        self.assigned_count = 0
        self.failed_count = 0

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="scheduler", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if not self._thread.is_alive():
                self._thread = None

    def _loop(self) -> None:
        while True:
            try:
                self.reconcile()
            except Exception as exc:  # noqa: BLE001 — keep reconciling
                logger.exception("reconcile failed: %s", exc)
            if self._stop.wait(self.cfg.interval):
                return

    # -- reconcile (ref controller.go:88-110) ------------------------------------

    def reconcile(self) -> int:
        """One pass; returns the number of requests processed."""
        backend = self.client.backend
        sg, sv, sp = SCHEDULING_GVR
        ug, uv, up = UAV_METRICS_GVR
        try:
            requests = backend.list_custom_resources(sg, sv, sp, None)
            uav_metrics = backend.list_custom_resources(ug, uv, up, None)
        except ClusterError as exc:
            logger.warning("reconcile list failed: %s", exc)
            return 0
        self.reconcile_count += 1
        processed = 0
        for req in requests:
            phase = (req.get("status") or {}).get("phase", "")
            if phase not in ("", "Pending"):
                continue  # only fresh requests (ref :117-120)
            self._process_request(req, uav_metrics)
            processed += 1
        return processed

    # -- per-request (ref controller.go:112-172) ----------------------------------

    def _process_request(self, req: dict[str, Any], uav_metrics: list[dict]) -> None:
        md = req.get("metadata", {})
        name = md.get("name", "")
        namespace = md.get("namespace", "")
        spec = req.get("spec", {}) or {}
        workload = spec.get("workload", {}) or {}

        if not workload.get("name") or not workload.get("namespace"):
            self._update_status(
                req,
                phase="Failed",
                message="workload name and namespace are required",
            )
            self.failed_count += 1
            return

        # Reference semantics (controller.go:174-221): no battery filter at
        # all when minBatteryPercent is absent or 0 — no silent default floor.
        min_battery = float(spec.get("minBatteryPercent") or 0.0)
        preferred = {str(n).lower() for n in (spec.get("preferredNodes") or [])}
        candidates = self._build_candidates(uav_metrics, min_battery, preferred)

        if not candidates:
            self._update_status(
                req,
                phase="Failed",
                message=f"no active UAV with battery >= {min_battery:.0f}%",
            )
            self.failed_count += 1
            logger.info("request %s/%s failed: no candidates", namespace, name)
            return

        best = max(candidates, key=lambda c: c.score)
        self._update_status(
            req,
            phase="Assigned",
            node=best.node_name,
            uav=best.uav_id,
            score=best.score,
            message=(
                f"assigned to {best.node_name} "
                f"(uav {best.uav_id}, battery {best.battery:.0f}%)"
            ),
        )
        self.assigned_count += 1
        logger.info(
            "request %s/%s assigned to %s (score %.1f)",
            namespace,
            name,
            best.node_name,
            best.score,
        )

    # -- candidates (ref controller.go:174-221) ------------------------------------

    def _build_candidates(
        self,
        uav_metrics: list[dict],
        min_battery: float,
        preferred: set[str],
    ) -> list[SchedulingCandidate]:
        tpu_nodes = self._tpu_nodes()
        out: list[SchedulingCandidate] = []
        for cr in uav_metrics:
            spec = cr.get("spec", {}) or {}
            status = cr.get("status", {}) or {}
            node = spec.get("node_name", "")
            battery = float(
                ((spec.get("battery") or {}).get("remaining_percent")) or 0.0
            )
            if not node:
                continue
            # Ref :198-200: only explicit non-"active" values disqualify —
            # an empty/missing collection_status is accepted; the comparison
            # is case-insensitive.
            cstatus = str(status.get("collection_status") or "")
            if cstatus and cstatus.lower() != "active":
                continue
            if min_battery > 0 and battery < min_battery:
                continue
            last = parse_rfc3339(status.get("last_update"))
            if last is not None and self._is_stale(
                last, float(status.get("heartbeat_interval_seconds") or 0.0)
            ):
                continue
            score = battery
            if node.lower() in preferred:
                score += PREFERRED_NODE_BONUS
            if node in tpu_nodes:
                score += self.cfg.tpu_node_bonus
            out.append(
                SchedulingCandidate(
                    node_name=node,
                    uav_id=spec.get("uav_id", ""),
                    battery=battery,
                    last_heartbeat=parse_rfc3339(status.get("last_update")),
                    score=score,
                )
            )
        return out

    def _is_stale(self, last_update, heartbeat_s: float) -> bool:
        """True when a CR's last_update is too old to trust its status."""
        age = (utcnow() - last_update).total_seconds()
        if heartbeat_s > 0 and self.cfg.stale_heartbeat_factor > 0:
            return age > self.cfg.stale_heartbeat_factor * heartbeat_s
        if self.cfg.stale_after_seconds > 0:
            return age > self.cfg.stale_after_seconds
        return False

    def _tpu_nodes(self) -> set[str]:
        try:
            return {
                n["metadata"]["name"]
                for n in self.client.backend.list_nodes()
                if int(
                    (n.get("status", {}).get("capacity", {}) or {}).get(
                        "google.com/tpu", 0
                    )
                    or 0
                )
                > 0
            }
        except ClusterError:
            return set()

    # -- status write (ref controller.go:223-250) -----------------------------------

    def _update_status(
        self,
        req: dict[str, Any],
        phase: str,
        node: str = "",
        uav: str = "",
        score: float = 0.0,
        message: str = "",
    ) -> None:
        sg, sv, sp = SCHEDULING_GVR
        status: dict[str, Any] = {
            "phase": phase,
            "lastUpdated": rfc3339(utcnow()),
        }
        if node:
            status["assignedNode"] = node
        if uav:
            status["assignedUAV"] = uav
        if score:
            status["score"] = score
        if message:
            status["message"] = message
        body = {
            "metadata": {
                "name": req["metadata"]["name"],
                "namespace": req["metadata"].get("namespace", ""),
            },
            "status": status,
        }
        try:
            self.client.backend.update_custom_resource_status(
                sg, sv, sp, req["metadata"].get("namespace") or None, body
            )
        except ClusterError as exc:
            logger.warning(
                "status update for %s failed: %s", req["metadata"].get("name"), exc
            )
