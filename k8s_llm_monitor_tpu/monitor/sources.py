"""Metric sources: node / pod / network / UAV.

Parity target: ``/root/reference/internal/metrics/sources/`` —
``node_metrics.go`` (capacity+usage join, metrics-server degradation
:47-52, disk = capacity−allocatable :117-124, health from conditions
:143-164), ``pod_metrics.go`` (requests/limits aggregation :105-119,
usage rates vs limit :162-171), ``network_metrics.go`` (cross-node pair
preference :133-206, bounded concurrent probes :83-109, HTTP-over-ping
preference :209-270), ``uav_metrics.go`` (agent pod discovery + state
pull :62-172).

TPU-first extension: nodes exposing ``google.com/tpu`` capacity surface
their chips through the accelerator fields (the reference zeroes GPU
fields with a "to be filled from CRDs" placeholder, node_metrics.go:188-197
— here the fields are actually populated).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from typing import Any, Callable

from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock
from k8s_llm_monitor_tpu.monitor.client import Client
from k8s_llm_monitor_tpu.monitor.cluster import (
    ClusterError,
    parse_cpu_millis,
    parse_mem_bytes,
)
from k8s_llm_monitor_tpu.monitor.metrics_types import (
    ContainerMetrics,
    NetworkMetrics,
    NodeMetrics,
    PodMetrics,
)
from k8s_llm_monitor_tpu.monitor.models import parse_rfc3339, utcnow
from k8s_llm_monitor_tpu.monitor.rtt import RTTTester

logger = logging.getLogger("monitor.sources")

PRESSURE_CONDITIONS = ("MemoryPressure", "DiskPressure", "PIDPressure", "NetworkUnavailable")
UAV_AGENT_LABEL = ("app", "uav-agent")
UAV_AGENT_PORT = 9090


class NodeMetricsSource:
    """Capacity from the node objects + usage from metrics.k8s.io."""

    def __init__(self, client: Client) -> None:
        self.client = client

    def collect(self) -> dict[str, NodeMetrics]:
        nodes = self.client.backend.list_nodes()
        usage_by_node: dict[str, dict] = {}
        try:
            for item in self.client.backend.node_usage():
                usage_by_node[item["metadata"]["name"]] = item.get("usage", {})
        except ClusterError as exc:
            # degrade to capacity-only (ref node_metrics.go:47-52)
            logger.warning("metrics-server unavailable, capacity-only: %s", exc)

        out: dict[str, NodeMetrics] = {}
        for node in nodes:
            out[node["metadata"]["name"]] = self._build(node, usage_by_node)
        return out

    def _build(self, node: dict, usage_by_node: dict[str, dict]) -> NodeMetrics:
        name = node["metadata"]["name"]
        status = node.get("status", {})
        capacity = status.get("capacity", {})
        allocatable = status.get("allocatable", {})
        usage = usage_by_node.get(name, {})

        m = NodeMetrics(node_name=name, timestamp=utcnow())
        m.cpu_capacity = parse_cpu_millis(capacity.get("cpu"))
        m.cpu_usage = parse_cpu_millis(usage.get("cpu"))
        if m.cpu_capacity > 0:
            m.cpu_usage_rate = m.cpu_usage / m.cpu_capacity * 100.0

        m.memory_capacity = parse_mem_bytes(capacity.get("memory"))
        m.memory_usage = parse_mem_bytes(usage.get("memory"))
        if m.memory_capacity > 0:
            m.memory_usage_rate = m.memory_usage / m.memory_capacity * 100.0

        # disk: estimate used as capacity − allocatable (ref :117-124)
        m.disk_capacity = parse_mem_bytes(capacity.get("ephemeral-storage"))
        alloc_disk = parse_mem_bytes(allocatable.get("ephemeral-storage"))
        if m.disk_capacity > 0 and alloc_disk > 0:
            m.disk_usage = max(0, m.disk_capacity - alloc_disk)
            m.disk_usage_rate = m.disk_usage / m.disk_capacity * 100.0

        # health: Ready + absence of pressure conditions (ref :143-164)
        conditions = status.get("conditions", [])
        ready = any(
            c.get("type") == "Ready" and c.get("status") == "True" for c in conditions
        )
        bad = [
            c["type"]
            for c in conditions
            if c.get("type") in PRESSURE_CONDITIONS and c.get("status") == "True"
        ]
        m.healthy = ready and not bad
        m.conditions = bad if ready else bad + ["NotReady"]
        m.labels = dict(node["metadata"].get("labels", {}) or {})

        # TPU accelerators through the accelerator fields
        tpu_count = int(capacity.get("google.com/tpu", 0) or 0)
        if tpu_count:
            model = m.labels.get("cloud.google.com/gke-tpu-accelerator", "tpu")
            m.gpu_count = tpu_count
            m.gpu_models = [model] * tpu_count
            m.gpu_usage = [0.0] * tpu_count
            m.custom_metrics["accelerator_type"] = "tpu"
        return m


class PodMetricsSource:
    """Per-namespace join of pod specs with metrics.k8s.io pod usage."""

    def __init__(self, client: Client, namespaces: list[str]) -> None:
        self.client = client
        self.namespaces = list(namespaces)

    def collect(self) -> dict[str, PodMetrics]:
        out: dict[str, PodMetrics] = {}
        for ns in self.namespaces:
            usage_by_pod: dict[str, dict] = {}
            try:
                for item in self.client.backend.pod_usage(ns):
                    usage_by_pod[item["metadata"]["name"]] = item
            except ClusterError as exc:
                logger.warning("pod usage unavailable in %s: %s", ns, exc)
            try:
                pods = self.client.backend.list_pods(ns)
            except ClusterError as exc:
                logger.warning("pod listing failed in %s: %s", ns, exc)
                continue
            for pod in pods:
                pm = self._build(pod, usage_by_pod)
                out[f"{pm.namespace}/{pm.pod_name}"] = pm
        return out

    def _build(self, pod: dict, usage_by_pod: dict[str, dict]) -> PodMetrics:
        md = pod.get("metadata", {})
        spec = pod.get("spec", {})
        status = pod.get("status", {})
        name = md.get("name", "")

        pm = PodMetrics(
            pod_name=name,
            namespace=md.get("namespace", ""),
            node_name=spec.get("nodeName", ""),
            timestamp=utcnow(),
            phase=status.get("phase", ""),
            start_time=parse_rfc3339(status.get("startTime")) or utcnow(),
        )

        usage_containers = {
            c.get("name"): c.get("usage", {})
            for c in usage_by_pod.get(name, {}).get("containers", [])
        }
        statuses = {s.get("name"): s for s in status.get("containerStatuses", [])}

        for c in spec.get("containers", []):
            cname = c.get("name", "")
            res = c.get("resources", {})
            requests = res.get("requests", {})
            limits = res.get("limits", {})
            cu = usage_containers.get(cname, {})
            cm = ContainerMetrics(
                name=cname,
                cpu_usage=parse_cpu_millis(cu.get("cpu")),
                memory_usage=parse_mem_bytes(cu.get("memory")),
                cpu_request=parse_cpu_millis(requests.get("cpu")),
                cpu_limit=parse_cpu_millis(limits.get("cpu")),
                memory_request=parse_mem_bytes(requests.get("memory")),
                memory_limit=parse_mem_bytes(limits.get("memory")),
            )
            pm.containers.append(cm)
            pm.cpu_usage += cm.cpu_usage
            pm.memory_usage += cm.memory_usage
            pm.cpu_request += cm.cpu_request
            pm.cpu_limit += cm.cpu_limit
            pm.memory_request += cm.memory_request
            pm.memory_limit += cm.memory_limit

        # usage rate relative to LIMIT (ref pod_metrics.go:162-171)
        if pm.cpu_limit > 0:
            pm.cpu_usage_rate = pm.cpu_usage / pm.cpu_limit * 100.0
        if pm.memory_limit > 0:
            pm.memory_usage_rate = pm.memory_usage / pm.memory_limit * 100.0

        pm.restarts = sum(int(s.get("restartCount", 0)) for s in statuses.values())
        pm.ready = bool(statuses) and all(s.get("ready") for s in statuses.values())
        return pm


class NetworkMetricsSource:
    """Probes RTT between automatically selected Running-pod pairs."""

    def __init__(
        self,
        client: Client,
        namespaces: list[str],
        max_pairs: int = 5,
        concurrency: int = 3,
        timeout: float = 10.0,
    ) -> None:
        self.client = client
        self.namespaces = list(namespaces)
        self.max_pairs = max_pairs
        self.concurrency = concurrency
        self.timeout = timeout
        self.tester = RTTTester(client)

    # -- pair selection (ref network_metrics.go:133-206) -----------------------

    def select_pod_pairs(self) -> list[tuple[str, str]]:
        """Up to ``max_pairs`` Running-pod pairs, cross-node pairs first."""
        pods = []
        for ns in self.namespaces:
            try:
                for p in self.client.get_pods(ns):
                    if p.status == "Running" and p.ip:
                        pods.append(p)
            except ClusterError as exc:
                logger.warning("pair selection: list pods %s failed: %s", ns, exc)
        refs = [f"{p.namespace}/{p.name}" for p in pods]
        # Bounded enumeration (the full product is O(n^2) in pod count, ref
        # network_metrics.go:166-167 caps both loops): stop once we have
        # max_pairs cross-node pairs; same-node pairs only fill a shortfall.
        cross, same = [], []
        for i in range(len(pods)):
            if len(cross) >= self.max_pairs:
                break
            for j in range(i + 1, len(pods)):
                if len(cross) >= self.max_pairs:
                    break
                pair = (refs[i], refs[j])
                if pods[i].node_name and pods[i].node_name != pods[j].node_name:
                    cross.append(pair)
                elif len(same) < self.max_pairs:
                    same.append(pair)
        return (cross + same)[: self.max_pairs]

    # -- collection (ref network_metrics.go:66-109) ----------------------------

    def collect(self) -> list[NetworkMetrics]:
        pairs = self.select_pod_pairs()
        if not pairs:
            return []
        results: list[NetworkMetrics | None] = [None] * len(pairs)
        sem = threading.Semaphore(self.concurrency)

        def probe(idx: int, pair: tuple[str, str]) -> None:
            with sem:
                results[idx] = self.test_pair(pair[0], pair[1])

        threads = [
            threading.Thread(target=probe, args=(i, p), daemon=True)
            for i, p in enumerate(pairs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout + 5)
        return [r for r in results if r is not None]

    # -- per-pair probe (ref network_metrics.go:209-270) -----------------------

    def test_pair(self, pod_a: str, pod_b: str) -> NetworkMetrics:
        nm = NetworkMetrics(source_pod=pod_a, target_pod=pod_b, timestamp=utcnow())
        try:
            result = self.tester.test_pod_connectivity(pod_a, pod_b)
        except ClusterError as exc:
            nm.error = str(exc)
            nm.test_method = "ping"
            return nm
        ping = [r for r in result.rtt_results if r.method.startswith("ping") and r.success]
        http = [r for r in result.rtt_results if r.method == "http" and r.success]
        if http:  # HTTP RTT preferred when both succeed
            nm.connected = True
            nm.rtt_ms = http[0].rtt_ms
            nm.test_method = "http"
        elif ping:
            nm.connected = True
            nm.rtt_ms = sum(r.rtt_ms for r in ping) / len(ping)
            nm.test_method = "ping"
        else:
            nm.test_method = "ping"
            errors = [r.error_message for r in result.rtt_results if r.error_message]
            nm.error = errors[0] if errors else "all probes failed"
        if result.rtt_results:
            nm.packet_loss = max(r.packet_loss for r in result.rtt_results)
        return nm


# fetcher seam so tests/dev mode can serve UAV state without real pod HTTP
StateFetcher = Callable[[str], dict[str, Any]]


def http_state_fetcher(url: str) -> dict[str, Any]:
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def http_command_poster(url: str, payload: dict[str, Any]) -> dict[str, Any]:
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


class UAVMetricsSource:
    """Pulls UAV state from per-node agent pods (``app=uav-agent``)."""

    def __init__(
        self,
        client: Client,
        namespace: str = "default",
        fetcher: StateFetcher | None = None,
        port: int = UAV_AGENT_PORT,
        poster=None,
    ) -> None:
        self.client = client
        self.namespace = namespace
        self.fetcher = fetcher or http_state_fetcher
        self.poster = poster or http_command_poster
        self.port = port

    def agent_pods(self):
        key, value = UAV_AGENT_LABEL
        return [
            p
            for p in self.client.get_pods(self.namespace)
            if p.status == "Running" and p.labels.get(key) == value and p.ip
        ]

    def collect(self) -> dict[str, dict[str, Any]]:
        """node name → raw UAV state dict (ref uav_metrics.go:62-172)."""
        out: dict[str, dict[str, Any]] = {}
        lock = make_lock("uav_source.merge")

        def pull(pod) -> None:
            url = f"http://{pod.ip}:{self.port}/api/v1/state"
            try:
                state = self.fetcher(url)
            except Exception as exc:
                logger.warning("UAV pull from %s (%s) failed: %s", pod.name, url, exc)
                return
            node = pod.node_name or state.get("node_name", pod.name)
            with lock:
                out[node] = state

        threads = [
            threading.Thread(target=pull, args=(p,), daemon=True)
            for p in self.agent_pods()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        return out

    def send_command(
        self, node: str, command: str, params: dict[str, Any] | None = None
    ) -> dict[str, Any]:
        """Push a flight command to the agent on ``node`` (ref
        uav_metrics.go:236-287 SendCommandToUAV — whose payload marshaling
        was an unfinished TODO, :254-266; here the body is actually sent).

        Commands map to the agent API: arm/disarm/takeoff/land/rtl/mode
        (monitor/agent.py)."""
        pod = next(
            (p for p in self.agent_pods() if p.node_name == node), None)
        if pod is None:
            raise ValueError(f"no running uav-agent pod on node {node!r}")
        url = f"http://{pod.ip}:{self.port}/api/v1/command/{command}"
        return self.poster(url, params or {})
