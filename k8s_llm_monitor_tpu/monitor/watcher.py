"""Reconnecting resource watchers + CRD watcher.

Parity target: ``/root/reference/internal/k8s/watcher.go`` (EventHandler
seam :16-21, per-namespace watch goroutines with reconnect-forever + 5 s
backoff :42-237; events deliver only Added :222-234) and
``crd_watcher.go`` (CRD discovery + dynamic per-CRD watches :85-240, CR
cache :353-383).

Deliberate fixes over the reference (SURVEY §2.4 "do NOT reproduce"):
- the CR-watch registry and cache are lock-guarded (ref mutates
  ``crdWatchers`` from multiple goroutines unlocked, crd_watcher.go:26,152);
- watcher threads are joinable and ``stop()`` actually tears them down
  (ref never joins its goroutines);
- the CR watch uses the CRD's storage version (ref builds a GVR with an
  empty Version, crd_watcher.go:148-151).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable

from k8s_llm_monitor_tpu.monitor.client import (
    Client,
    convert_event,
    convert_pod,
    convert_service,
)
from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock
from k8s_llm_monitor_tpu.monitor.cluster import ClusterError, WatchStream
from k8s_llm_monitor_tpu.monitor.models import (
    CRDEvent,
    CRDInfo,
    CustomResourceInfo,
    EventInfo,
    PodInfo,
    ServiceInfo,
    parse_rfc3339,
    utcnow,
)
from k8s_llm_monitor_tpu.resilience.retry import Backoff


def _reconnect_backoff(cap_s: float) -> Backoff:
    """The shared reconnect curve: start fast (a blip reconnects in sub-
    second), grow to the configured cap (the old fixed delay) so a down
    apiserver is not hammered.  ``attempts`` is irrelevant here — watch
    loops reconnect forever and only stop() ends them."""
    return Backoff(base_s=min(0.25, cap_s), cap_s=cap_s, mult=2.0,
                   jitter=0.2, attempts=2)

logger = logging.getLogger("monitor.watcher")


class EventHandler:
    """Fan-out seam for reactive consumers (ref watcher.go:16-21)."""

    def on_pod_update(self, event_type: str, pod: PodInfo) -> None: ...

    def on_service_update(self, event_type: str, service: ServiceInfo) -> None: ...

    def on_event(self, event: EventInfo) -> None: ...

    def on_crd_event(self, event: CRDEvent) -> None: ...


class Watcher:
    """Watches pods/services/events across namespaces with auto-reconnect.

    One thread per (namespace, resource); each runs watch → drain → on
    stream close, sleep ``reconnect_delay`` and re-watch, forever, until
    ``stop()``.
    """

    def __init__(
        self,
        client: Client,
        handler: EventHandler,
        namespaces: list[str] | None = None,
        reconnect_delay: float = 5.0,
    ) -> None:
        self.client = client
        self.handler = handler
        self.namespaces = list(namespaces or client.namespaces())
        self.reconnect_delay = reconnect_delay
        self.backoff = _reconnect_backoff(reconnect_delay)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._streams: list[WatchStream] = []
        self._lock = make_lock("watcher.streams")

    def start(self) -> None:
        for ns in self.namespaces:
            for kind in ("pods", "services", "events"):
                t = threading.Thread(
                    target=self._watch_loop,
                    args=(kind, ns),
                    name=f"watch-{kind}-{ns}",
                    daemon=True,
                )
                self._threads.append(t)
                t.start()
        logger.info(
            "watcher started for namespaces %s (%d threads)",
            self.namespaces,
            len(self._threads),
        )

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        # Snapshot under the lock: watch threads may append to these lists
        # concurrently, and iterating while another thread mutates can raise
        # or skip an entry (leaving a thread never joined).
        with self._lock:
            streams = list(self._streams)
            threads = list(self._threads)
        for s in streams:
            s.close()
        for t in threads:
            t.join(timeout=timeout)
        with self._lock:
            self._threads.clear()

    def _register(self, stream: WatchStream) -> None:
        # Close immediately if stop() ran between watch() and registration,
        # otherwise the thread would block forever on an unclosable stream.
        with self._lock:
            self._streams.append(stream)
        if self._stop.is_set():
            stream.close()

    def _watch_loop(self, kind: str, namespace: str) -> None:
        fail_streak = 0
        while not self._stop.is_set():
            try:
                stream = self.client.watch(kind, namespace)
            except ClusterError as exc:
                logger.warning("watch %s/%s failed: %s; retrying", kind, namespace, exc)
                self._stop.wait(self.backoff.delay(fail_streak))
                fail_streak += 1
                continue
            self._register(stream)
            delivered = False
            try:
                for event_type, obj in stream:
                    if self._stop.is_set():
                        return
                    delivered = True
                    self._dispatch(kind, event_type, obj)
            except Exception:
                logger.exception("watch %s/%s dispatch error", kind, namespace)
            finally:
                with self._lock:
                    if stream in self._streams:
                        self._streams.remove(stream)
            # stream closed server-side → reconnect (ref watcher.go:84-87).
            # A stream that delivered events was a real session: reconnect
            # from the bottom of the curve.  One that closed without ever
            # delivering counts as another failure.
            if delivered:
                fail_streak = 0
            self._stop.wait(self.backoff.delay(fail_streak))
            if not delivered:
                fail_streak += 1

    def _dispatch(self, kind: str, event_type: str, obj: dict[str, Any]) -> None:
        if kind == "pods":
            self.handler.on_pod_update(event_type, convert_pod(obj))
        elif kind == "services":
            self.handler.on_service_update(event_type, convert_service(obj))
        elif kind == "events" and event_type == "ADDED":
            # only Added, like ref watcher.go:222-234
            self.handler.on_event(convert_event(obj))


def convert_crd(raw: dict[str, Any]) -> CRDInfo:
    md = raw.get("metadata", {})
    spec = raw.get("spec", {})
    names = spec.get("names", {})
    conds = raw.get("status", {}).get("conditions", [])
    established = any(
        c.get("type") == "Established" and c.get("status") == "True" for c in conds
    )
    versions = [v.get("name", "") for v in spec.get("versions", [])]
    stored = any(v.get("storage") for v in spec.get("versions", []))
    return CRDInfo(
        name=md.get("name", ""),
        group=spec.get("group", ""),
        kind=names.get("kind", ""),
        scope=spec.get("scope", "Namespaced"),
        versions=versions,
        plural=names.get("plural", ""),
        singular=names.get("singular", ""),
        established=established,
        stored=stored,
        creation_time=parse_rfc3339(md.get("creationTimestamp")) or utcnow(),
    )


def storage_version(raw_crd: dict[str, Any]) -> str:
    for v in raw_crd.get("spec", {}).get("versions", []):
        if v.get("storage"):
            return v.get("name", "v1")
    versions = raw_crd.get("spec", {}).get("versions", [])
    return versions[0].get("name", "v1") if versions else "v1"


class CRDWatcher:
    """Watches CRDs themselves; per established CRD, watches its CRs.

    Maintains a lock-guarded CR cache keyed ``group/kind/namespace``
    (ref crd_watcher.go:353-383) with accessors ``get_crds`` /
    ``get_custom_resources``.
    """

    def __init__(
        self,
        client: Client,
        handler: EventHandler,
        reconnect_delay: float = 5.0,
    ) -> None:
        self.client = client
        self.handler = handler
        self.reconnect_delay = reconnect_delay
        self.backoff = _reconnect_backoff(reconnect_delay)
        self._stop = threading.Event()
        self._lock = make_lock("crd_watcher.state")
        self._threads: list[threading.Thread] = []
        self._streams: list[WatchStream] = []
        self._cr_watched: set[str] = set()  # crd metadata.name
        self._crds: dict[str, CRDInfo] = {}
        # group/kind/namespace -> {name: CustomResourceInfo}
        self._cr_cache: dict[str, dict[str, CustomResourceInfo]] = {}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._discover_and_watch()
        t = threading.Thread(target=self._crd_watch_loop, name="watch-crds", daemon=True)
        self._threads.append(t)
        t.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        # Snapshot under the lock: watch threads may append to these lists
        # concurrently, and iterating while another thread mutates can raise
        # or skip an entry (leaving a thread never joined).
        with self._lock:
            streams = list(self._streams)
            threads = list(self._threads)
        for s in streams:
            s.close()
        for t in threads:
            t.join(timeout=timeout)
        with self._lock:
            self._threads.clear()

    def _register(self, stream: WatchStream) -> None:
        with self._lock:
            self._streams.append(stream)
        if self._stop.is_set():
            stream.close()

    # -- discovery (ref crd_watcher.go:178-201) -------------------------------

    def _discover_and_watch(self) -> None:
        try:
            crds = self.client.backend.list_crds()
        except ClusterError as exc:
            logger.warning("CRD discovery failed: %s", exc)
            return
        for raw in crds:
            info = convert_crd(raw)
            with self._lock:
                self._crds[info.name] = info
            if info.established:
                self._ensure_cr_watch(raw)

    def _ensure_cr_watch(self, raw_crd: dict[str, Any]) -> None:
        if self._stop.is_set():
            return  # shutting down — never spawn a watch stop() could miss
        name = raw_crd.get("metadata", {}).get("name", "")
        t = threading.Thread(
            target=self._cr_watch_loop,
            args=(raw_crd,),
            name=f"watch-cr-{name}",
            daemon=True,
        )
        with self._lock:
            if name in self._cr_watched:
                return
            self._cr_watched.add(name)
            self._threads.append(t)
        t.start()

    # -- watch loops ----------------------------------------------------------

    def _crd_watch_loop(self) -> None:
        fail_streak = 0
        while not self._stop.is_set():
            try:
                stream = self.client.backend.watch_crds()
            except ClusterError as exc:
                logger.warning("CRD watch failed: %s; retrying", exc)
                self._stop.wait(self.backoff.delay(fail_streak))
                fail_streak += 1
                continue
            fail_streak = 0
            self._register(stream)
            try:
                for event_type, raw in stream:
                    if self._stop.is_set():
                        return
                    info = convert_crd(raw)
                    with self._lock:
                        if event_type == "DELETED":
                            self._crds.pop(info.name, None)
                        else:
                            self._crds[info.name] = info
                    # Established may arrive on the later MODIFIED event, not
                    # the initial ADDED (real API servers set the condition
                    # asynchronously); _ensure_cr_watch dedups, so check both.
                    if event_type in ("ADDED", "MODIFIED") and info.established:
                        self._ensure_cr_watch(raw)
            finally:
                with self._lock:
                    if stream in self._streams:
                        self._streams.remove(stream)
            self._stop.wait(self.backoff.delay(0))

    def _cr_watch_loop(self, raw_crd: dict[str, Any]) -> None:
        spec = raw_crd.get("spec", {})
        group = spec.get("group", "")
        names = spec.get("names", {})
        kind = names.get("kind", "")
        plural = names.get("plural", "")
        version = storage_version(raw_crd)
        namespaced = spec.get("scope", "Namespaced") == "Namespaced"
        fail_streak = 0
        while not self._stop.is_set():
            try:
                stream = self.client.backend.watch_custom_resources(
                    group, version, plural, None if not namespaced else ""
                )
            except ClusterError as exc:
                logger.warning("CR watch %s.%s failed: %s", plural, group, exc)
                self._stop.wait(self.backoff.delay(fail_streak))
                fail_streak += 1
                continue
            fail_streak = 0
            self._register(stream)
            try:
                for event_type, obj in stream:
                    if self._stop.is_set():
                        return
                    self._handle_cr_event(event_type, obj, group, kind, version)
            finally:
                with self._lock:
                    if stream in self._streams:
                        self._streams.remove(stream)
            self._stop.wait(self.backoff.delay(0))

    def _handle_cr_event(
        self, event_type: str, obj: dict[str, Any], group: str, kind: str, version: str
    ) -> None:
        from k8s_llm_monitor_tpu.monitor.client import convert_custom_resource

        info = convert_custom_resource(obj, group, kind)
        cache_key = f"{group}/{kind}/{info.namespace}"
        with self._lock:
            bucket = self._cr_cache.setdefault(cache_key, {})
            if event_type == "DELETED":
                bucket.pop(info.name, None)
            else:
                bucket[info.name] = info
        self.handler.on_crd_event(
            CRDEvent(
                type={"ADDED": "Added", "MODIFIED": "Modified", "DELETED": "Deleted"}.get(
                    event_type, event_type
                ),
                kind=kind,
                group=group,
                version=version,
                name=info.name,
                namespace=info.namespace,
                object=dict(obj),
                timestamp=utcnow(),
            )
        )

    # -- accessors (ref crd_watcher.go:386-407) --------------------------------

    def get_crds(self) -> list[CRDInfo]:
        with self._lock:
            return list(self._crds.values())

    def get_custom_resources(self) -> dict[str, list[CustomResourceInfo]]:
        with self._lock:
            return {k: list(v.values()) for k, v in self._cr_cache.items()}
