"""Metrics data models + behavior helpers.

Parity target: ``/root/reference/pkg/metrics/types.go`` (NodeMetrics …
MetricsSnapshot, types.go:8-148; helper methods types.go:151-199). Field
names are the wire names; thresholds match the reference exactly
(pressure 80/80/90, over-limit 90%, quality bands <10/<50/<100 ms).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime
from typing import Any

from k8s_llm_monitor_tpu.monitor.models import omitempty, utcnow


@dataclass
class NodeMetrics:
    node_name: str = ""
    timestamp: datetime = field(default_factory=utcnow)

    # CPU (millicores)
    cpu_capacity: int = 0
    cpu_usage: int = 0
    cpu_usage_rate: float = 0.0

    # memory (bytes)
    memory_capacity: int = 0
    memory_usage: int = 0
    memory_usage_rate: float = 0.0

    # disk (bytes)
    disk_capacity: int = 0
    disk_usage: int = 0
    disk_usage_rate: float = 0.0

    # network (from CRDs or probes)
    network_latency: float = 0.0  # ms
    network_bandwidth: float = 0.0  # Mbps

    # accelerators (from CRD extensions; the TPU build also reports TPU
    # chips through these fields — see sources.py)
    gpu_count: int = 0
    gpu_models: list[str] = field(default_factory=list)
    gpu_usage: list[float] = field(default_factory=list)
    gpu_memory_total: list[int] = field(default_factory=list)  # MB
    gpu_memory_used: list[int] = field(default_factory=list)  # MB

    healthy: bool = True
    conditions: list[str] = field(default_factory=list)
    labels: dict[str, str] = field(default_factory=dict)
    custom_metrics: dict[str, Any] = field(default_factory=dict, metadata=omitempty())

    # --- behavior (ref types.go:151-162) ---

    def available_resources(self) -> tuple[float, float, float]:
        """(cpu cores, memory GB, disk GB) still available."""
        cpu = (self.cpu_capacity - self.cpu_usage) / 1000.0
        mem = (self.memory_capacity - self.memory_usage) / 1024**3
        disk = (self.disk_capacity - self.disk_usage) / 1024**3
        return cpu, mem, disk

    def is_under_pressure(self) -> bool:
        return (
            self.cpu_usage_rate > 80.0
            or self.memory_usage_rate > 80.0
            or self.disk_usage_rate > 90.0
        )


@dataclass
class ContainerMetrics:
    name: str = ""
    cpu_usage: int = 0
    memory_usage: int = 0
    cpu_request: int = 0
    cpu_limit: int = 0
    memory_request: int = 0
    memory_limit: int = 0


@dataclass
class PodMetrics:
    pod_name: str = ""
    namespace: str = ""
    node_name: str = ""
    timestamp: datetime = field(default_factory=utcnow)

    cpu_usage: int = 0  # millicores
    memory_usage: int = 0  # bytes

    cpu_request: int = 0
    cpu_limit: int = 0
    memory_request: int = 0
    memory_limit: int = 0

    cpu_usage_rate: float = 0.0  # vs limit
    memory_usage_rate: float = 0.0  # vs limit

    containers: list[ContainerMetrics] = field(default_factory=list)

    phase: str = ""
    ready: bool = False
    restarts: int = 0
    start_time: datetime = field(default_factory=utcnow)

    # --- behavior (ref types.go:165-184) ---

    def resource_utilization(self) -> tuple[float, float]:
        """(cpu %, mem %) relative to requests."""
        cpu = (
            self.cpu_usage / self.cpu_request * 100.0 if self.cpu_request > 0 else 0.0
        )
        mem = (
            self.memory_usage / self.memory_request * 100.0
            if self.memory_request > 0
            else 0.0
        )
        return cpu, mem

    def is_over_limit(self) -> bool:
        if self.cpu_limit > 0 and self.cpu_usage >= self.cpu_limit * 0.9:
            return True
        if self.memory_limit > 0 and self.memory_usage >= self.memory_limit * 0.9:
            return True
        return False


@dataclass
class NetworkMetrics:
    source_pod: str = ""
    target_pod: str = ""
    timestamp: datetime = field(default_factory=utcnow)

    connected: bool = False
    error: str = field(default="", metadata=omitempty())

    rtt_ms: float = 0.0
    packet_loss: float = 0.0  # 0-100

    bandwidth_mbps: float = field(default=0.0, metadata=omitempty())
    test_method: str = ""  # ping | http | tcp

    def quality(self) -> str:
        """Quality bands per ref types.go:187-199."""
        if not self.connected:
            return "disconnected"
        if self.rtt_ms < 10:
            return "excellent"
        if self.rtt_ms < 50:
            return "good"
        if self.rtt_ms < 100:
            return "fair"
        return "poor"


@dataclass
class ClusterMetrics:
    timestamp: datetime = field(default_factory=utcnow)

    total_nodes: int = 0
    healthy_nodes: int = 0
    total_pods: int = 0
    running_pods: int = 0

    total_cpu: int = 0  # millicores
    used_cpu: int = 0
    cpu_usage_rate: float = 0.0

    total_memory: int = 0  # bytes
    used_memory: int = 0
    memory_usage_rate: float = 0.0

    total_gpus: int = 0
    available_gpus: int = 0

    health_status: str = "healthy"  # healthy | warning | critical
    issues: list[str] = field(default_factory=list, metadata=omitempty())


@dataclass
class MetricsSnapshot:
    timestamp: datetime = field(default_factory=utcnow)
    node_metrics: dict[str, NodeMetrics] = field(default_factory=dict)
    pod_metrics: dict[str, PodMetrics] = field(default_factory=dict)  # ns/name
    network_metrics: list[NetworkMetrics] = field(default_factory=list)
    cluster_metrics: ClusterMetrics | None = None
