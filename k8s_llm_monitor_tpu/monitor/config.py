"""Typed configuration tree + YAML/env loader.

Parity target: ``/root/reference/internal/config/config.go`` — same tree
(server / k8s / llm / storage / monitoring / metrics / analysis / logging,
config.go:12-102), same defaults (config.go:132-169), same env override
behavior (viper ``AutomaticEnv`` with ``.``→``_``, config.go:106-113, plus
the OPENAI_* aliases at config.go:172-182).

Differences by design (TPU-first): ``llm.provider`` gains the in-tree
``"tpu"`` value (serving the Analysis Engine from the local JAX engine
instead of a remote OpenAI call) and an ``llm.tpu`` sub-block selecting the
model preset; the reference's remote-provider fields are kept for the
OpenAI-compatible fallback path.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any

import yaml

#: Central registry of every *explicit* project-prefixed env key read
#: anywhere in the package.  Keys derived generically by ``_apply_env``
#: (config path ``fleet.role`` -> ``FLEET_ROLE``) are NOT listed — they
#: are computed from the dataclass tree.  Values are either a
#: ``Class.field`` the key overrides (validated against the package AST
#: by ``graftcheck --contracts``) or ``runtime:<module>`` for toggles
#: with no config field, owned and read by that module.  The env
#: contract checker enforces: every explicit read is registered, every
#: entry is read and documented, every target exists.
ENV_KEYS: dict[str, str] = {
    # engine kernel-path overrides (env wins over EngineConfig)
    "K8SLLM_KV_DTYPE": "EngineConfig.kv_dtype",
    "K8SLLM_PREFILL_PATH": "EngineConfig.prefill_path",
    "K8SLLM_DECODE_PATH": "EngineConfig.decode_path",
    "K8SLLM_TP_OVERLAP": "EngineConfig.tp_overlap",
    # reference-compat aliases (config.go:172-182)
    "OPENAI_API_KEY": "LLMConfig.api_key",
    "OPENAI_BASE_URL": "LLMConfig.base_url",
    # runtime toggles: no config field by design — they must work
    # before/without a loaded Config (crash paths, chaos drills, tests)
    "K8SLLM_TRACE_SAMPLE": "runtime:observability/tracing.py",
    "K8SLLM_TRACE_SEED": "runtime:observability/tracing.py",
    "K8SLLM_FLIGHT_DIR": "runtime:observability/flight.py",
    "K8SLLM_FAULTS": "runtime:resilience/faults.py",
    "K8SLLM_JOURNAL_FSYNC": "runtime:resilience/journal.py",
    "K8SLLM_LOCKCHECK": "runtime:devtools/lockcheck.py",
    "K8SLLM_LOCKCHECK_HOLD_MS": "runtime:devtools/lockcheck.py",
    "K8SLLM_TENANT_ENFORCE": "runtime:resilience/tenancy.py",
    "K8SLLM_TENANT_DEFAULT": "runtime:resilience/tenancy.py",
    "K8SLLM_REMEDIATE_APPROVE": "runtime:remediation/executor.py",
}


@dataclass
class ServerConfig:
    host: str = "0.0.0.0"
    port: int = 8080
    debug: bool = False


@dataclass
class K8sConfig:
    kubeconfig: str = ""
    namespace: str = "default"
    watch_namespaces: list[str] = field(default_factory=lambda: ["default"])


@dataclass
class TPULLMConfig:
    """In-tree TPU inference backend knobs (new; no reference equivalent)."""

    model: str = "llama-1b"  # preset name in models/config.py PRESETS
    checkpoint: str = ""  # HF checkpoint dir ('' => random-init dev weights)
    # "int8" = weight-only quantization; "w8a8" = int8 weights + dynamic
    # per-token activation int8 (s8 x s8 prefill, measured ~1.4x the bf16
    # matmul rate on v5e); '' = bf16.
    # W8A8 is the declared serving default: it is the only mode that meets
    # every short-leg SLO in the driver-captured bench artifacts
    # (BENCH_r04/r05), and its logits parity against the bf16 path is
    # tested (tests/test_quantize.py::test_w8a8_forward_parity).
    quantize: str = "w8a8"
    mesh_shape: str = ""  # e.g. "1,1,8" for data,seq,model; '' => single chip
    max_batch: int = 32
    kv_blocks: int = 512
    # Persistent XLA compilation cache: warm server restarts skip the
    # multi-minute prefill/decode compile ladder.  '' disables.
    compile_cache_dir: str = ".jax_cache"
    # Prompt-lookup speculative decoding draft length (serving/spec.py);
    # 0 disables.  Every sampling mode speculates (greedy bit-identically;
    # sampled — incl. top-k/top-p — distribution-exactly), emitting up to
    # spec_k+1 tokens per verify forward when the output quotes its
    # context.  ON by default for the monitor: diagnosis answers are
    # template-heavy (they quote pod names, container states, and log
    # lines straight out of the evidence prompt — exactly the regime
    # prompt-lookup drafts for), and the downside is bounded twice over:
    # the AcceptanceEMA kill-switch (spec_min_accept below) auto-disables
    # drafting per request class when measured acceptance cannot pay for
    # the verify forwards, and brownout (resilience/slo.py ladder) turns
    # speculation off wholesale under pressure.  Set 0 to opt out.
    spec_k: int = 4
    # Acceptance floor for the per-request-class speculative kill-switch
    # (serving/spec.py AcceptanceEMA): when a class's accepted-tokens-per-
    # lane-round EMA drops below this, drafting auto-disables for that
    # class (re-probing periodically).  Exported as `spec_accept_ema`.
    spec_min_accept: float = 1.2


@dataclass
class LLMConfig:
    provider: str = "tpu"  # "tpu" (in-tree) | "openai" | "template"
    api_key: str = ""
    base_url: str = ""
    model: str = "gpt-4"
    max_tokens: int = 2000
    temperature: float = 0.1
    timeout: int = 30
    tpu: TPULLMConfig = field(default_factory=TPULLMConfig)


@dataclass
class RedisConfig:
    host: str = "localhost"
    port: int = 6379
    password: str = ""
    db: int = 0


@dataclass
class PostgresConfig:
    host: str = "localhost"
    port: int = 5432
    user: str = ""
    password: str = ""
    database: str = ""


@dataclass
class StorageConfig:
    type: str = "memory"
    redis: RedisConfig = field(default_factory=RedisConfig)
    postgres: PostgresConfig = field(default_factory=PostgresConfig)


@dataclass
class MonitoringConfig:
    metrics_interval: int = 30
    event_retention: int = 168  # hours (ref config.go default)
    log_retention: int = 24  # hours (ref config.go default)


@dataclass
class MetricsConfig:
    enabled: bool = True
    collect_interval: int = 30
    namespaces: list[str] = field(default_factory=lambda: ["default"])
    enable_node: bool = True
    enable_pod: bool = True
    enable_network: bool = False
    enable_uav: bool = True
    enable_custom: bool = False
    cache_retention: int = 300
    max_pod_pairs: int = 5
    network_timeout: int = 10


@dataclass
class AnalysisConfig:
    enable_prediction: bool = True  # ref config.go default
    enable_auto_fix: bool = False
    max_context_events: int = 100
    # Embedding anomaly detector (analysis/anomaly.py): "" disables;
    # an ENCODER_PRESETS name ("tiny-encoder", "bge-large") random-inits;
    # a directory path loads a BertModel-family HF checkpoint.
    embedding_model: str = ""


@dataclass
class DiagnosisConfig:
    """Standing watcher→LLM diagnosis pipeline (diagnosis/pipeline.py).
    New; no reference equivalent — the reference never closed the
    monitor→LLM loop."""

    enabled: bool = True
    # Burst detector: >= burst_threshold Warning events inside window_s
    # triggers one root-cause query; cooldown_s suppresses immediate
    # re-triggers while the same incident keeps emitting events.
    burst_threshold: int = 5
    window_s: float = 60.0
    cooldown_s: float = 120.0
    # Context assembly bounds: the event ring the assembler selects from,
    # how many events each query includes (embedding top-k when
    # analysis.embedding_model is set, else the most recent), and the hard
    # character cap on the rendered context block.
    max_context_events: int = 64
    context_top_k: int = 8
    max_context_chars: int = 2000
    # Verdict ring exposed at GET /api/v1/diagnoses.
    history: int = 64
    # Multi-turn follow-up sessions (diagnosis/session.py): idle TTL and
    # the LRU cap on concurrently pinned session contexts.
    session_ttl_s: float = 600.0
    max_sessions: int = 16


@dataclass
class LifecycleConfig:
    """Crash-safe serving lifecycle (resilience/journal.py +
    serving/supervisor.py + cmd/server.py signal handlers).  New; no
    reference equivalent — the Go reference had no engine to supervise."""

    # Request WAL directory; '' disables journaling (the supervisor still
    # rebuilds and replays in-process requests).
    journal_dir: str = ""
    journal_fsync: str = "interval"  # always | interval | never
    journal_segment_mb: int = 4
    # SIGTERM/SIGINT: how long to wait for inflight generations before the
    # process exits.  Keep below the pod's terminationGracePeriodSeconds
    # minus the preStop sleep (deployments/monitor-server.yaml).
    drain_grace_s: float = 20.0
    # Supervisor: engine rebuilds allowed before giving up, and how stale
    # the step-loop heartbeat may go (with work pending) before the loop
    # counts as wedged.
    max_restarts: int = 3
    heartbeat_timeout_s: float = 30.0
    restart_backoff_s: float = 0.5


@dataclass
class FleetConfig:
    """Fleet tier (fleet/): router role fronting N engine replicas.
    New; no reference equivalent — the Go reference was single-process."""

    # Replica base URLs the router fronts, e.g.
    # "http://engine-0.engine:8080,http://engine-1.engine:8080"
    # (FLEET_REPLICAS env, comma-separated).  Empty = this process is a
    # plain replica; the router role refuses to start without it.
    replicas: list[str] = field(default_factory=list)
    policy: str = "affinity"  # affinity | least_loaded | round_robin
    # Prompt-prefix length (tokens) hashed for affinity routing; keep at
    # or above the shared cluster-context preamble so same-context queries
    # stay on the replica whose PrefixCache holds their pages.
    affinity_prefix_tokens: int = 64
    probe_interval_s: float = 5.0
    connect_timeout_s: float = 2.0
    read_timeout_s: float = 60.0
    # Per-replica circuit breaker (resilience/retry.py semantics).
    breaker_failures: int = 3
    breaker_cooldown_s: float = 5.0
    # Mid-stream failover budget per request.
    max_failovers: int = 2
    # Hedged dispatch: fire a second replica when the first shows no token
    # after the EMA-p95 TTFT delay (docs/fleet.md).  fixed_delay_s > 0
    # pins the delay (bench/tests); 0 uses the online estimate.
    hedge_enabled: bool = False
    hedge_min_delay_s: float = 0.05
    hedge_fixed_delay_s: float = 0.0
    # SLO-class routing (resilience/slo.py): batch requests only spill to
    # a non-affinity replica whose load score is below this fraction of
    # capacity; interactive requests always route least-loaded.
    batch_spill_threshold: float = 0.75
    # Disaggregation role of THIS process (FLEET_ROLE env on replicas):
    # "prefill" replicas take new prompts and hand the finished prefix to
    # a "decode" replica over the KVX1 migration path; "unified" does
    # both.  The router reads each replica's role from its stats
    # heartbeat — misconfigured or mixed fleets degrade to unified
    # dispatch, never to dropped requests (docs/fleet.md).
    role: str = "unified"  # prefill | decode | unified
    # Best-effort prefix handout when a replica announces draining: at
    # most this many cached prefixes are offered to their new rendezvous
    # owners via export_prefix/install_prefix before the replica leaves.
    drain_sweep_budget: int = 8


@dataclass
class TelemetryConfig:
    """Fleet telemetry plane (observability/timeseries.py + signals.py):
    the in-tree time-series store, the signal scraper, and the derived
    autoscaler/anomaly contract behind GET /api/v1/signals.  New; no
    reference equivalent."""

    enabled: bool = True
    # Scraper cadence and store bounds: points kept per series, series
    # allowed in the store (label-cardinality blast-radius cap).
    scrape_interval_s: float = 2.0
    ring_points: int = 512
    max_series: int = 2048
    # Default trailing window for derived signals and /api/v1/timeseries.
    window_s: float = 60.0
    ema_half_life_s: float = 10.0
    # scale_hint thresholds: per-class queue-token growth rate that reads
    # as "scale up", and the brownout dwell fraction (share of window
    # samples at rung >= degraded) that does the same.
    queue_growth_up_tok_s: float = 50.0
    brownout_dwell_up: float = 0.5
    # Per-class TTFT budgets (seconds) for sustained-breach detection.
    ttft_budget_interactive_s: float = 1.0
    ttft_budget_standard_s: float = 2.5
    ttft_budget_batch_s: float = 10.0
    # Anomaly edge-trigger cooldown per (target, flag), and whether
    # anomalies feed the diagnosis pipeline as self_monitor events.
    anomaly_cooldown_s: float = 30.0
    feed_diagnosis: bool = True
    # Replica probe-staleness multiple (router role): stats older than
    # this many probe intervals get NaN markers, not frozen values.
    stale_after_probes: float = 3.0
    # Trailing seconds of the series window snapshotted into flight-
    # recorder crash artifacts (v2 "signals" block).
    flight_window_s: float = 30.0


@dataclass
class AutoscaleConfig:
    """Elasticity controller (fleet/autoscaler.py): closes the telemetry
    plane's sense loop by acting on per-target ``scale_hint``s through
    per-role StatefulSet scale subresources (or an in-process LocalReplica
    pool under test).  New; no reference equivalent."""

    enabled: bool = False
    # Decision cadence (the controller also exposes a tick() seam so
    # tests drive it with a fake clock).
    interval_s: float = 10.0
    # Per-role replica bounds.  Unknown/unified targets count against the
    # "unified" role.
    min_prefill: int = 1
    max_prefill: int = 4
    min_decode: int = 1
    max_decode: int = 4
    min_unified: int = 1
    max_unified: int = 4
    # Hysteresis: scale-down requires the role's hints to agree "down"
    # continuously for the dwell; any executed action opens a cooldown
    # during which the controller refuses to act again.
    scale_down_dwell_s: float = 60.0
    cooldown_s: float = 30.0
    # Flap damping: more than this many per-role direction changes inside
    # the window refuses further actions until hints settle.
    flap_window_s: float = 120.0
    flap_max_flips: int = 3
    # Kube execution: per-role StatefulSet names under `namespace`;
    # every scale is issued dry-run first, then for real, through the
    # hardened client's retry/breaker path.
    namespace: str = "monitoring"
    statefulset_prefill: str = "engine-prefill"
    statefulset_decode: str = "engine-decode"
    statefulset_unified: str = "engine"
    dry_run_first: bool = True
    # Per-verb circuit breaker on the scale subresource.
    breaker_failures: int = 3
    breaker_cooldown_s: float = 30.0


@dataclass
class RemediationConfig:
    """Closed-loop remediation (remediation/executor.py): the diagnosis
    pipeline's plan stage plus the gated executor and verification turn.
    New; no reference equivalent — the Go reference stopped at verdicts."""

    # Plan stage on/off.  Enabled by default: plans are cheap, grammar
    # -bounded, and observe-only until `execute` (or a per-plan approval)
    # says otherwise.
    enabled: bool = True
    # The big switch: False (default) stores plans without touching the
    # cluster; an explicit POST /api/v1/remediations/<id>/approve still
    # executes that one plan.  True executes non-destructive plans
    # automatically (destructive verbs additionally need the approval
    # gate — K8SLLM_REMEDIATE_APPROVE=1 or per-plan approval).
    execute: bool = False
    # Every mutation is validated with a dry-run call first (server-side
    # dryRun=All on the real client, simulated validation on the fake).
    dry_run_first: bool = True
    # Post-action verification turn (session-pinned diagnosis + per-verb
    # state predicate) and its capped escalation ladder.
    verify: bool = True
    max_retries: int = 2
    # Per-verb circuit breaker around the cluster backend.
    breaker_failures: int = 3
    breaker_cooldown_s: float = 30.0
    # Rate limits: minimum seconds between executions of the same verb,
    # and of the same (verb, target) pair.
    verb_interval_s: float = 5.0
    target_interval_s: float = 60.0
    # Idempotency: an identical (verb, target, trigger) execution within
    # this window is refused as a replay (supervisor replays, double
    # approvals).
    replay_window_s: float = 300.0
    # Stored-record ring size for GET /api/v1/remediations.
    history: int = 128


@dataclass
class TenancyConfig:
    """Multi-tenant admission quotas + KV fairness (resilience/tenancy.py).
    New; no reference equivalent — the Go reference had no admission layer
    to partition."""

    enabled: bool = True
    # Refuse over-quota requests with tenant-tagged 429s.  False keeps the
    # full per-tenant accounting but never refuses (single-tenant default);
    # K8SLLM_TENANT_ENFORCE=1 flips enforcement on without a config change.
    enforce: bool = True
    # Per-tenant request-rate bucket; rate 0 leaves the dimension
    # unlimited (burst 0 derives from the rate).
    requests_per_s: float = 0.0
    request_burst: float = 0.0
    # Per-tenant generated-token quota bucket: max_tokens is reserved at
    # admission and the unused remainder refunded at settlement.
    tokens_per_s: float = 0.0
    token_burst: float = 0.0
    # KV fairness: fraction of resident prefix-cache blocks (device) /
    # bytes (host tier) one tenant may hold while another is resident;
    # 1.0 disables the cap.
    max_kv_share: float = 1.0
    # Exporter cardinality cap: per-tenant metric families emit the top-K
    # tenants by admitted requests plus one aggregate "other" bucket.
    top_k_metrics: int = 8
    # Governor state cap: longest-idle tenants with nothing in flight are
    # evicted past this many distinct tenants.
    max_tenants: int = 1024


@dataclass
class LoggingConfig:
    level: str = "info"
    format: str = "json"  # ref config.go default
    output: str = "stdout"


@dataclass
class Config:
    server: ServerConfig = field(default_factory=ServerConfig)
    k8s: K8sConfig = field(default_factory=K8sConfig)
    llm: LLMConfig = field(default_factory=LLMConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    monitoring: MonitoringConfig = field(default_factory=MonitoringConfig)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    analysis: AnalysisConfig = field(default_factory=AnalysisConfig)
    diagnosis: DiagnosisConfig = field(default_factory=DiagnosisConfig)
    lifecycle: LifecycleConfig = field(default_factory=LifecycleConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    autoscale: AutoscaleConfig = field(default_factory=AutoscaleConfig)
    remediation: RemediationConfig = field(
        default_factory=RemediationConfig)
    tenancy: TenancyConfig = field(default_factory=TenancyConfig)
    logging: LoggingConfig = field(default_factory=LoggingConfig)


def _coerce(value: str, target: Any) -> Any:
    """Coerce an env-var string to the type of the current field value."""
    if isinstance(target, bool):
        return value.strip().lower() in ("1", "true", "yes", "on")
    if isinstance(target, int):
        return int(value)
    if isinstance(target, float):
        return float(value)
    if isinstance(target, list):
        return [v.strip() for v in value.split(",") if v.strip()]
    return value


def _apply_dict(obj: Any, data: dict[str, Any], path: str = "") -> None:
    """Recursively overlay a parsed-YAML dict onto the dataclass tree."""
    for key, value in (data or {}).items():
        norm = str(key).replace("-", "_")
        if not dataclasses.is_dataclass(obj) or not hasattr(obj, norm):
            continue  # unknown keys are ignored, like viper
        current = getattr(obj, norm)
        if dataclasses.is_dataclass(current) and isinstance(value, dict):
            _apply_dict(current, value, f"{path}{norm}.")
        elif value is not None:
            if isinstance(current, (bool, int, float)) and isinstance(value, str):
                value = _coerce(value, current)
            setattr(obj, norm, value)


def _apply_env(obj: Any, prefix: str = "") -> None:
    """Overlay env vars: config path ``a.b.c`` reads ``A_B_C``.

    Mirrors viper AutomaticEnv with the ``.``→``_`` replacer
    (ref config.go:106-113).
    """
    for f in dataclasses.fields(obj):
        current = getattr(obj, f.name)
        env_key = (prefix + f.name).upper()
        if dataclasses.is_dataclass(current):
            _apply_env(current, prefix + f.name + "_")
        elif env_key in os.environ:
            setattr(obj, f.name, _coerce(os.environ[env_key], current))


def load_config(path: str | None = None) -> Config:
    """Load config: defaults ← YAML file ← env vars ← OPENAI_* aliases.

    Precedence and alias behavior match ref config.go:105-182. A missing
    file is not an error when ``path`` is empty/None (defaults-only boot,
    the reference's dev mode); an explicit path that doesn't exist raises.
    """
    cfg = Config()
    if path:
        with open(path) as fh:
            data = yaml.safe_load(fh) or {}
        _apply_dict(cfg, data)
    _apply_env(cfg)
    # Compatibility aliases (ref config.go:172-182).
    if os.environ.get("OPENAI_API_KEY"):
        cfg.llm.api_key = os.environ["OPENAI_API_KEY"]
    if os.environ.get("OPENAI_BASE_URL"):
        cfg.llm.base_url = os.environ["OPENAI_BASE_URL"]
    # Keep metrics namespaces in sync with watch namespaces when only the
    # k8s block was configured (the reference wires cfg.K8s.WatchNamespaces
    # into the manager directly, cmd/server/main.go:62-72).
    if cfg.k8s.watch_namespaces and cfg.metrics.namespaces == ["default"]:
        cfg.metrics.namespaces = list(cfg.k8s.watch_namespaces)
    return cfg
