"""Kubernetes intelligent-monitoring control plane.

This package is the product layer of the framework: the cluster-facing
monitoring capability set of the reference (config, cluster access, watchers,
metrics collection, network diagnosis, UAV telemetry, scheduling) plus the
Analysis Engine the reference only sketched, wired to the in-tree TPU
inference stack (``k8s_llm_monitor_tpu.serving``).

Module map (reference parity cited per module):

- ``config``        — typed config tree + YAML/env loader
                      (ref internal/config/config.go)
- ``models``        — cluster data models / JSON contract
                      (ref pkg/models/models.go, pkg/models/scheduler.go)
- ``metrics_types`` — metrics data models (ref pkg/metrics/types.go)
- ``cluster``       — ClusterBackend seam + FakeCluster in-memory backend
- ``client``        — high-level cluster client (ref internal/k8s/client.go)
- ``watcher``       — reconnecting resource/CRD watchers
                      (ref internal/k8s/watcher.go, crd_watcher.go)
- ``rtt``           — in-pod exec RTT probes (ref internal/k8s/rtt_tester.go)
- ``network``       — pod-communication analyzer (ref internal/k8s/network.go)
- ``sources``       — node/pod/network/UAV metric sources
                      (ref internal/metrics/sources/)
- ``manager``       — snapshot collection loop (ref internal/metrics/manager.go)
- ``uav``           — MAVLink telemetry simulator (ref pkg/uav/)
- ``agent``         — per-node UAV agent (ref cmd/uav-agent/main.go)
- ``scheduler``     — UAV-aware scheduling controller
                      (ref internal/scheduler/controller.go)
- ``analysis``      — the Analysis Engine: evidence assembly + TPU LLM backends
- ``server``        — the HTTP JSON API (ref cmd/server/main.go)
"""

from k8s_llm_monitor_tpu.monitor.config import Config, load_config

__all__ = ["Config", "load_config"]
