"""Prometheus text-format self-observability exporter.

The reference has no ``/metrics`` endpoint — its self-observability is
logrus lines only (SURVEY §5.5; reference internal/metrics/manager.go:317-319
logs per-collection durations and nothing is scrapeable).  This module
renders the monitor's own health as Prometheus exposition text (version
0.0.4) for the ``GET /metrics`` route:

  * serving engine gauges/counters: queue depth, active slots, free KV
    blocks, prefill/decode-step/preemption totals, TTFT histogram;
  * metrics-manager collection stats and snapshot sizes;
  * TPU/accelerator gauges (device kind, HBM bytes in use) when a JAX
    device is live — ``jax.local_devices()[0].memory_stats()``.

No client library: exposition text is trivial to emit and the zero-dep
constraint (stdlib + jax only) matches the rest of the monitor plane.
"""

from __future__ import annotations

import math
import re
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from k8s_llm_monitor_tpu.monitor.server import MonitorServer

_PREFIX = "k8s_llm_monitor"

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABELS_RE = re.compile(
    r'^\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\}$')
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")


class _Writer:
    def __init__(self, openmetrics: bool = False) -> None:
        self.lines: list[str] = []
        self.openmetrics = openmetrics

    def metric(self, name: str, mtype: str, help_: str,
               samples: list[tuple[str, float]]) -> None:
        """samples: [(labels_suffix_or_empty, value)]"""
        full = f"{_PREFIX}_{name}"
        self.lines.append(f"# HELP {full} {help_}")
        self.lines.append(f"# TYPE {full} {mtype}")
        for labels, value in samples:
            if isinstance(value, float) and math.isinf(value):
                value = "+Inf" if value > 0 else "-Inf"
            elif isinstance(value, float) and math.isnan(value):
                value = "NaN"
            self.lines.append(f"{full}{labels} {value}")

    def histogram(self, name: str, help_: str, hist) -> None:
        """Render an ``observability.metrics.ClassHistogram`` as one
        Prometheus histogram family with a ``class`` label per SLO class.
        In OpenMetrics mode each bucket with an exemplar gets the
        ``# {trace_id="..."} value ts`` annotation — the dashboard's jump
        from a bad latency bucket to the trace that landed in it."""
        full = f"{_PREFIX}_{name}"
        self.lines.append(f"# HELP {full} {help_}")
        self.lines.append(f"# TYPE {full} histogram")
        for cls in hist.classes():
            cum, total, count, exemplars = hist.series(cls)
            edges = [str(b) for b in hist.buckets] + ["+Inf"]
            for i, (le, c) in enumerate(zip(edges, cum)):
                line = f'{full}_bucket{{class="{cls}",le="{le}"}} {c}'
                ex = exemplars.get(i) if self.openmetrics else None
                if ex is not None:
                    tid, value, ts = ex
                    line += (f' # {{trace_id="{tid}"}} '
                             f"{round(value, 6)} {round(ts, 3)}")
                self.lines.append(line)
            self.lines.append(
                f'{full}_sum{{class="{cls}"}} {round(total, 6)}')
            self.lines.append(f'{full}_count{{class="{cls}"}} {count}')

    def render(self) -> str:
        body = "\n".join(self.lines) + "\n"
        if self.openmetrics:
            body += "# EOF\n"
        return body


def lint_exposition(text: str) -> list[str]:
    """Validate Prometheus/OpenMetrics text exposition: every sample
    belongs to a family with exactly one HELP and one TYPE, names and
    label blocks are well-formed, values parse, and special markers use
    the canonical spellings (``NaN``, ``+Inf``).  Returns human-readable
    error strings; empty means clean.  Runs at render time (the exporter
    appends its own error count as a metric) and in the tier-1 lint test.
    """
    errors: list[str] = []
    helps: dict[str, int] = {}
    types: dict[str, str] = {}
    for n, raw in enumerate(text.splitlines(), start=1):
        line = raw.rstrip()
        if not line or line == "# EOF":
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            kind = line[2:6]
            parts = line.split(" ", 3)
            if len(parts) < 4 or not parts[3].strip():
                errors.append(f"line {n}: bare {kind} with no text")
                continue
            fam = parts[2]
            if not _METRIC_NAME_RE.match(fam):
                errors.append(f"line {n}: invalid family name {fam!r}")
            if kind == "HELP":
                helps[fam] = helps.get(fam, 0) + 1
                if helps[fam] > 1:
                    errors.append(f"line {n}: duplicate HELP for {fam}")
            else:
                if fam in types:
                    errors.append(f"line {n}: duplicate TYPE for {fam}")
                types[fam] = parts[3].strip()
            continue
        if line.startswith("#"):
            continue  # free-form comment
        # Sample line; OpenMetrics exemplars hang off " # ".
        sample, _, exemplar = line.partition(" # ")
        m = _SAMPLE_RE.match(sample.strip())
        if m is None:
            errors.append(f"line {n}: unparseable sample {line!r}")
            continue
        name, labels, value = m.groups()
        fam = name
        for suffix in ("_bucket", "_sum", "_count"):
            if (name.endswith(suffix) and name[: -len(suffix)] in types
                    and types[name[: -len(suffix)]] == "histogram"):
                fam = name[: -len(suffix)]
                break
        if fam not in types:
            errors.append(f"line {n}: sample {name} has no TYPE")
        if fam not in helps:
            errors.append(f"line {n}: sample {name} has no HELP")
        if labels and not _LABELS_RE.match(labels):
            errors.append(f"line {n}: malformed labels {labels!r}")
        if value in ("nan", "inf", "-inf", "+inf", "Inf"):
            errors.append(
                f"line {n}: non-canonical marker {value!r} "
                "(use NaN/+Inf/-Inf)")
        else:
            try:
                float(value)
            except ValueError:
                errors.append(f"line {n}: bad value {value!r}")
        if exemplar:
            ex = exemplar.strip()
            if not ex.startswith("{") or "}" not in ex:
                errors.append(f"line {n}: malformed exemplar {ex!r}")
    for fam in types:
        if fam not in helps:
            errors.append(f"family {fam}: TYPE without HELP")
    for fam in helps:
        if fam not in types:
            errors.append(f"family {fam}: HELP without TYPE")
    return errors


def _engine_metrics(w: _Writer, engine) -> None:
    w.metric("engine_queue_depth", "gauge",
             "Requests waiting for admission",
             [("", engine.queue_depth)])
    w.metric("engine_active_slots", "gauge",
             "Decode lanes currently occupied",
             [("", engine.active_slots)])
    w.metric("engine_slots_total", "gauge",
             "Configured decode lanes",
             [("", engine.ecfg.max_slots)])
    w.metric("engine_free_kv_blocks", "gauge",
             "KV cache blocks available in the pool",
             [("", engine.allocator.free_blocks)])
    w.metric("engine_kv_blocks_total", "gauge",
             "Configured KV cache blocks",
             [("", engine.ecfg.num_blocks)])
    w.metric("engine_prefills_total", "counter",
             "Prompts ingested via prefill",
             [("", engine.prefills)])
    w.metric("engine_decode_steps_total", "counter",
             "Device decode steps executed",
             [("", engine.steps)])
    w.metric("engine_preemptions_total", "counter",
             "Recompute-preemptions under KV pressure",
             [("", engine.preemptions)])
    if engine.prefix_cache is not None:
        w.metric("engine_prefix_cache_hits_total", "counter",
                 "Admissions served a cached prompt prefix",
                 [("", engine.prefix_cache.hits)])
        w.metric("engine_prefix_cache_misses_total", "counter",
                 "Admissions that found no cached prefix",
                 [("", engine.prefix_cache.misses)])
        w.metric("engine_prefix_deferrals_total", "counter",
                 "Requests whose admission waited for a publishing "
                 "same-prefix lane (cold-burst dedup)",
                 [("", engine.prefix_deferrals)])
    # KV tiering (serving/kv_tier.py): per-tier byte accounting plus the
    # spill/restore flow between them.  The host sample is an explicit
    # NaN when no spill buffer is configured — an absent-vs-zero mixup
    # across a fleet scrape would hide "this replica cannot spill".
    tier_fn = getattr(engine, "kv_tier_stats", None)
    if callable(tier_fn):
        t = tier_fn()
        has_host = getattr(engine, "host_kv_tier", None) is not None
        w.metric("kv_tier_bytes", "gauge",
                 "KV bytes held per tier (device = configured resident "
                 "pool incl. quantization scales; host = spilled prefix "
                 "entries; NaN host = no spill buffer configured)",
                 [('{tier="device"}', t["device_bytes"]),
                  ('{tier="host"}', t["host_bytes"] if has_host
                   else float("nan"))])
        quant = t["kv_quant"] or "none"
        w.metric("kv_quant_info", "gauge",
                 "Resident KV quantization mode and page dtype "
                 "(1 = active)",
                 [(f'{{mode="{quant}",dtype="{t["page_dtype"]}"}}', 1)])
        w.metric("kv_spills_total", "counter",
                 "Cold prefix entries evicted to the host tier instead of "
                 "dropped", [("", t["spills"])])
        w.metric("kv_restores_total", "counter",
                 "Host-tier prefix entries rehydrated into device pages "
                 "on a hit", [("", t["restores"])])
        w.metric("kv_host_lost_total", "counter",
                 "Host-tier entries dropped under host-buffer pressure "
                 "(next hit falls back to prompt replay)",
                 [("", t["host_lost"])])
    # Tier-aware admission headroom (engine.admission_headroom_tokens):
    # the token capacity should_shed()'s kv_admission clause admits
    # against — free device blocks plus, under the "tier" policy, the
    # spillable prefix-cache span the host tier has room for.
    headroom_fn = getattr(engine, "admission_headroom_tokens", None)
    if callable(headroom_fn):
        w.metric("kv_admission_headroom_tokens", "gauge",
                 "KV tokens the admission capacity clause can still "
                 "place (device free + host-spillable under "
                 "kv_admission=tier)",
                 [("", headroom_fn())])
    w.metric("engine_chunk_shrinks_total", "counter",
             "Chunked-prefill rounds shrunk below the configured bucket "
             "because interactive-class work was queued",
             [("", getattr(engine, "chunk_shrinks", 0))])
    w.metric("engine_chunk_bucket", "gauge",
             "Prefill bucket used by the most recent chunked round "
             "(0 until a chunked prefill has run)",
             [("", getattr(engine, "last_chunk_bucket", 0))])
    w.metric("engine_spec_tokens_total", "counter",
             "Tokens emitted by speculative-decode dispatches",
             [("", engine.spec_tokens)])
    w.metric("engine_spec_verify_steps_total", "counter",
             "Verify forwards run by speculative-decode dispatches",
             [("", engine.spec_verify_steps)])
    w.metric("engine_spec_lane_rounds_total", "counter",
             "Active lane-rounds across spec verify forwards (divide "
             "spec_tokens by this for per-lane acceptance)",
             [("", engine.spec_lane_rounds)])
    # Per-request-class accepted-length EMA (serving/spec.py:AcceptanceEMA):
    # the signal behind the adaptive drafting kill-switch.  Absent until a
    # class has a measurement — a missing class label means "never probed",
    # not zero acceptance.
    ema_fn = getattr(engine, "spec_accept_ema", None)
    snap = ema_fn() if callable(ema_fn) else {}
    if snap:
        w.metric("spec_accept_ema", "gauge",
                 "Accepted tokens per lane-round EMA, by request class; "
                 "drafting auto-disables below the configured floor",
                 [(f'{{class="{k}"}}', round(v, 4))
                  for k, v in sorted(snap.items())])

    # Mesh topology: one sample per axis of the serving mesh, so the
    # dashboard can tell a TP-8 v5e slice from a single chip without
    # scraping the deployment spec.  Off-mesh engines emit nothing.
    mesh_fn = getattr(engine, "mesh_axes", None)
    axes = mesh_fn() if callable(mesh_fn) else {}
    if axes:
        w.metric("mesh_axes", "gauge",
                 "Serving mesh axis sizes (data/seq/model)",
                 [(f'{{axis="{a}"}}', int(n))
                  for a, n in sorted(axes.items())])
        w.metric("engine_decode_collective_share", "gauge",
                 "Estimated ICI (collective) share of a TP decode step, "
                 "from the decode profile's byte model; 0 until "
                 "profile_decode_phases() has run",
                 [("", round(getattr(engine, "decode_collective_share",
                                     0.0), 4))])
        w.metric("engine_tp_overlap", "gauge",
                 "1 when the hand-staged reduce-scatter/all-gather decode "
                 "schedule is active (parallel/overlap.py); 0 = GSPMD "
                 "reference program",
                 [("", 1 if getattr(engine, "tp_overlap", False) else 0)])
        w.metric("engine_decode_collective_hidden_share", "gauge",
                 "Fraction of the per-step ring wire time the overlap "
                 "schedule hides under compute (measured on TPU, "
                 "analytic in dryrun); 0 until estimate_hidden_share() "
                 "has run",
                 [("", round(getattr(
                     engine, "decode_collective_hidden_share", 0.0), 4))])

    # Decode-step phase attribution (fused fast-path observability).
    # attn/sample are populated by engine.profile_decode_phases() — a
    # bench/admin probe, never run on scrape — so they read 0.0 until a
    # profile has run.  host_gap is a live EMA updated at every decode
    # reconcile and is the one to alert on: it should sit near 0 when
    # dispatch-ahead hides device latency.
    path = getattr(engine, "decode_path", "unknown")
    w.metric("engine_decode_path_info", "gauge",
             "Selected decode attention path (1 = active)",
             [(f'{{path="{path}"}}', 1)])
    w.metric("engine_decode_attn_ms", "gauge",
             "Profiled per-step paged-attention cost at long context",
             [("", round(getattr(engine, "decode_attn_ms", 0.0), 4))])
    w.metric("engine_decode_sample_ms", "gauge",
             "Profiled per-step on-device sampling cost",
             [("", round(getattr(engine, "decode_sample_ms", 0.0), 4))])
    w.metric("engine_decode_host_gap_ms", "gauge",
             "EMA of host time blocked per decode/spec reconcile "
             "(~0 when dispatch-ahead hides device latency)",
             [("", round(getattr(engine, "decode_host_gap_ms", 0.0), 4))])

    # Prefill fast-path attribution, mirroring the decode trio: which
    # path the engine selected (flash paged-prefill kernel vs dense XLA),
    # how long prefill calls take, and which bucket sizes production
    # actually dispatches (the 4096/8192 rungs exist only on flash).
    ppath = getattr(engine, "prefill_path", "dense")
    w.metric("engine_prefill_path_info", "gauge",
             "Selected prefill attention path (1 = active)",
             [(f'{{path="{ppath}"}}', 1)])
    w.metric("engine_prefill_attn_ms", "gauge",
             "EMA of per-prefill-call wall time (dispatch to reconcile), "
             "admission and chunk rounds alike",
             [("", round(getattr(engine, "prefill_attn_ms", 0.0), 4))])
    bucket_rounds = getattr(engine, "prefill_bucket_rounds", {})
    if bucket_rounds:
        w.metric("engine_prefill_bucket_rounds_total", "counter",
                 "Prefill rounds dispatched per bucket size (admission "
                 "and chunk rounds)",
                 [(f'{{bucket="{b}"}}', n)
                  for b, n in sorted(bucket_rounds.items())])

    # Prometheus histogram: cumulative buckets + sum + count.
    cumulative = 0
    samples = []
    for le, n in zip(engine.ttft_buckets, engine.ttft_counts):
        cumulative += n
        samples.append((f'_bucket{{le="{le}"}}', cumulative))
    cumulative += engine.ttft_counts[-1]
    samples.append(('_bucket{le="+Inf"}', cumulative))
    w.metric("engine_ttft_seconds", "histogram",
             "Time to first token per request", samples)
    w.lines.append(f"{_PREFIX}_engine_ttft_seconds_sum {engine.ttft_sum}")
    w.lines.append(f"{_PREFIX}_engine_ttft_seconds_count {engine.ttft_count}")


def _latency_histograms(w: _Writer, engine) -> None:
    """Per-SLO-class latency histograms (observability.metrics), with
    trace-id exemplars in OpenMetrics mode.  Families appear once a class
    has at least one observation; absent class labels mean "no traffic of
    that class yet", matching the per-class EMA NaN convention above."""
    hists = (
        ("request_ttft_seconds",
         "Time to first token per request, by SLO class",
         getattr(engine, "hist_ttft", None)),
        ("request_e2e_seconds",
         "Submit-to-final-token latency per request, by SLO class",
         getattr(engine, "hist_e2e", None)),
        ("request_queue_wait_seconds",
         "Queue wait before admission per request, by SLO class",
         getattr(engine, "hist_queue_wait", None)),
        ("decode_step_seconds",
         "Per-token decode segment time (segment wall time / steps), "
         "by SLO class",
         getattr(engine, "hist_decode_step", None)),
    )
    for name, help_, hist in hists:
        if hist is not None:
            w.histogram(name, help_, hist)


_HEALTH_STATES = ("healthy", "degraded", "draining", "unhealthy")


def _resilience_metrics(w: _Writer, engine, service) -> None:
    """Health state machine + failure-recovery counters (PR 2), plus the
    SLO-class admission/eviction/brownout gauges (resilience/slo.py)."""
    from k8s_llm_monitor_tpu.resilience.slo import BROWNOUT_NAMES, SLO_CLASSES

    if service is not None:
        state = service.health.state()
        w.metric("health_state", "gauge",
                 "Live health state (1 = current state)",
                 [(f'{{state="{s}"}}', 1 if s == state else 0)
                  for s in _HEALTH_STATES])
        w.metric("sheds_total", "counter",
                 "Submissions refused by load shedding",
                 [("", service.health.sheds)])
        w.metric("shed_total", "counter",
                 "Submissions refused by class-aware load shedding, "
                 "by SLO class",
                 [(f'{{class="{c}"}}',
                   service.shed_count_by_class.get(c, 0))
                  for c in SLO_CLASSES])
        bsnap = service.brownout.snapshot()
        w.metric("brownout_state", "gauge",
                 "Brownout ladder rung (1 = current rung); degraded "
                 "disables hedging/spec-decode and clamps batch budgets, "
                 "draining pauses diagnosis triggers",
                 [(f'{{state="{s}"}}', 1 if i == bsnap["level"] else 0)
                  for i, s in enumerate(BROWNOUT_NAMES)])
        w.metric("brownout_escalations_total", "counter",
                 "Brownout rung increases (immediate on health decline)",
                 [("", bsnap["escalations"])])
        w.metric("brownout_recoveries_total", "counter",
                 "Brownout rung decreases (one rung per recovery dwell)",
                 [("", bsnap["recoveries"])])
    w.metric("engine_watchdog_trips_total", "counter",
             "Dispatch watchdog expirations (pipeline resets)",
             [("", engine.watchdog_trips)])
    w.metric("engine_dispatch_failures_total", "counter",
             "Dispatch or reconcile failures recovered by the engine",
             [("", engine.dispatch_failures)])
    w.metric("engine_deadline_expired_total", "counter",
             "Requests failed by deadline/queue-TTL enforcement",
             [("", engine.deadline_expired)])
    w.metric("engine_requeues_total", "counter",
             "Slots recompute-requeued after a pipeline reset",
             [("", engine.requeues)])
    w.metric("engine_slot_wait_seconds", "gauge",
             "EMA of queue wait before a request wins a slot "
             "(load-shedding signal)",
             [("", round(engine.slot_wait_ema_s, 6))])
    # Per-class admission/latency EMAs.  A class with no sample yet emits
    # an explicit NaN (the constrained_decode_overhead_ms pattern): the
    # fleet router proxies replica /metrics, so an absent label would
    # silently mix "never measured" into the 0.0 population across
    # replicas.  Counters stay 0-valued — zero events IS the measurement.
    w.metric("queue_wait_ms", "gauge",
             "EMA of queue wait before a slot, by SLO class "
             "(NaN = no admission of this class yet)",
             [(f'{{class="{c}"}}',
               round(engine.slot_wait_ema_by_class[c] * 1000.0, 3)
               if c in engine.slot_wait_ema_by_class else float("nan"))
              for c in SLO_CLASSES])
    w.metric("engine_ttft_ema_seconds", "gauge",
             "EMA of time to first token, by SLO class "
             "(NaN = no completion of this class yet)",
             [(f'{{class="{c}"}}',
               round(engine.ttft_ema_by_class[c], 6)
               if c in engine.ttft_ema_by_class else float("nan"))
              for c in SLO_CLASSES])
    w.metric("preemptions_total", "counter",
             "Recompute-preemptions (involuntary KV pressure + voluntary "
             "class eviction), by evicted lane's SLO class",
             [(f'{{class="{c}"}}', engine.preemptions_by_class.get(c, 0))
              for c in SLO_CLASSES])
    w.metric("engine_brownout_clamps_total", "counter",
             "Batch max_tokens clamps applied while degraded or worse",
             [("", engine.brownout_clamps)])


_LIFECYCLE_STATES = ("serving", "rebuilding", "terminating", "stopped",
                     "failed")


def _lifecycle_metrics(w: _Writer, sup) -> None:
    """Crash-safe lifecycle: supervisor restarts + journal replay (PR 4)."""
    snap = sup.snapshot()
    w.metric("lifecycle_state", "gauge",
             "Serving lifecycle state (1 = current state)",
             [(f'{{state="{s}"}}', 1 if s == snap["state"] else 0)
              for s in _LIFECYCLE_STATES])
    w.metric("engine_restarts_total", "counter",
             "Engine rebuilds after a dead/wedged step loop",
             [("", snap["restarts"])])
    w.metric("journal_replayed_total", "counter",
             "Requests re-admitted from the journal or in-process tracking "
             "(rebuild replay + warm start)",
             [("", snap["replayed_total"])])
    w.metric("journal_bytes", "gauge",
             "Request WAL size on disk across live segments",
             [("", snap["journal_bytes"])])


def _kube_breaker_metrics(w: _Writer, breaker) -> None:
    states = ("closed", "open", "half-open")
    state = breaker.state
    w.metric("kube_breaker_state", "gauge",
             "Kube apiserver circuit breaker state (1 = current state)",
             [(f'{{state="{s}"}}', 1 if s == state else 0) for s in states])
    w.metric("kube_breaker_trips_total", "counter",
             "Times the apiserver circuit breaker opened",
             [("", breaker.trips)])
    w.metric("kube_breaker_rejections_total", "counter",
             "Apiserver calls refused while the breaker was open",
             [("", breaker.rejections)])


def _manager_metrics(w: _Writer, manager) -> None:
    w.metric("collections_total", "counter",
             "Metrics collection cycles completed",
             [("", manager.collect_count)])
    w.metric("collection_duration_seconds", "gauge",
             "Duration of the most recent collection cycle",
             [("", round(manager.last_collect_duration, 6))])
    snap = manager.get_latest_snapshot()
    w.metric("snapshot_nodes", "gauge", "Nodes in the latest snapshot",
             [("", len(snap.node_metrics))])
    w.metric("snapshot_pods", "gauge", "Pods in the latest snapshot",
             [("", len(snap.pod_metrics))])
    w.metric("snapshot_network_pairs", "gauge",
             "Probed pod pairs in the latest snapshot",
             [("", len(snap.network_metrics))])
    w.metric("snapshot_uavs", "gauge", "UAVs in the latest snapshot",
             [("", len(manager.get_uav_metrics()))])


def _fleet_metrics(w: _Writer, router) -> None:
    """Fleet-tier gauges (router role): per-replica dispatch state plus
    the router's hedging/failover/affinity counters (PR 5)."""
    snap = router.registry.snapshot()
    ready, inflight, hit_rate, dispatches, failures = [], [], [], [], []
    ages, roles, draining = [], [], []
    for rid, rep in sorted(snap.items()):
        label = f'{{replica="{rid}"}}'
        ready.append((label, 1 if rep["ready"] else 0))
        inflight.append((label, rep["inflight"]))
        hit_rate.append((label, rep["prefix_hit_rate"]))
        dispatches.append((label, rep["dispatches"]))
        failures.append((label, rep["failures"]))
        age = rep.get("probe_age_s")
        ages.append((label, age if age is not None else float("nan")))
        role = rep.get("role", "unified")
        roles.append((f'{{replica="{rid}",role="{role}"}}', 1))
        draining.append((label, 1 if rep.get("draining") else 0))
    if ready:
        w.metric("fleet_replica_ready", "gauge",
                 "Replica readiness as the router sees it", ready)
        w.metric("fleet_replica_inflight", "gauge",
                 "Router-side requests in flight per replica", inflight)
        w.metric("fleet_replica_prefix_hit_rate", "gauge",
                 "Prefix-cache hit rate from the replica's last stats probe",
                 hit_rate)
        w.metric("fleet_replica_dispatches_total", "counter",
                 "Requests the router dispatched to each replica",
                 dispatches)
        w.metric("fleet_replica_failures_total", "counter",
                 "Dispatch/stream failures the router observed per replica",
                 failures)
        # NaN = never probed, not "0 seconds ago" — a frozen stats row
        # must read as stale, never fresh (the scraper marks replicas
        # stale past stale_after_probes × probe interval).
        w.metric("fleet_scrape_age_s", "gauge",
                 "Seconds since each replica's last completed stats probe "
                 "(NaN = never probed)", ages)
        # Disaggregation (PR 14): the role is a label, the value is a
        # constant 1 — join on {replica} to slice any fleet metric by role.
        w.metric("fleet_replica_role", "gauge",
                 "Replica serving role (prefill/decode/unified) as an "
                 "info-style gauge", roles)
        w.metric("fleet_replica_draining", "gauge",
                 "1 while the replica announces draining (router stops "
                 "dispatching; in-flight streams finish)", draining)
    c = router.counters()
    w.metric("fleet_affinity_hits_total", "counter",
             "Dispatches that landed on the policy's preferred replica",
             [("", c["affinity_hits"])])
    w.metric("fleet_affinity_spills_total", "counter",
             "Dispatches diverted off the preferred replica (saturation or "
             "breaker)", [("", c["affinity_spills"])])
    w.metric("fleet_hedges_fired_total", "counter",
             "Hedged dispatches fired after the EMA-p95 TTFT delay",
             [("", c["hedges_fired"])])
    w.metric("fleet_hedges_won_total", "counter",
             "Hedged dispatches whose second replica produced the first "
             "token", [("", c["hedges_won"])])
    w.metric("fleet_failovers_total", "counter",
             "Mid-stream failovers (replica died; request resumed "
             "elsewhere)", [("", c["failovers"])])
    w.metric("fleet_sheds_total", "counter",
             "Requests refused because no replica would take them",
             [("", c["sheds"])])
    w.metric("fleet_hedge_delay_seconds", "gauge",
             "Current hedge trigger delay (EMA-p95 of TTFT)",
             [("", round(router.hedge_delay_s(), 6))])
    # Cross-replica prefix migration (PR 10).  All outcomes are emitted
    # 0-valued from the start so rate() works before the first attempt;
    # unexpected outcome strings (future engine verdicts) still show up.
    mig = dict(c.get("prefix_migrations") or {})
    outcomes = ["installed", "cached", "miss", "owner_down",
                "incompatible", "nospace", "error"]
    outcomes += sorted(o for o in mig if o not in outcomes)
    w.metric("fleet_prefix_migrations_total", "counter",
             "Prefix migrations attempted on affinity misses, by outcome "
             "(installed = pages moved instead of re-prefilling)",
             [(f'{{outcome="{o}"}}', mig.get(o, 0)) for o in outcomes])
    # Disaggregated prefill→decode handoffs (PR 14).  Landing outcomes
    # (decode/local/replay) and failure causes share one family: the
    # causes explain why a handoff degraded to local decode.  All known
    # outcomes pre-seed at 0 so rate() works before the first handoff.
    hand = dict(c.get("handoffs") or {})
    h_outcomes = ["decode", "local", "replay", "no_decode", "owner_down",
                  "miss", "torn", "install_timeout", "nospace",
                  "incompatible", "dispatch_failed", "error"]
    h_outcomes += sorted(o for o in hand if o not in h_outcomes)
    w.metric("fleet_handoffs_total", "counter",
             "Prefill->decode handoff attempts by landing (decode = "
             "disaggregated, local = degraded to prefill replica, replay "
             "= owner died) and by failure cause",
             [(f'{{outcome="{o}"}}', hand.get(o, 0)) for o in h_outcomes])
    w.metric("fleet_drain_sweeps_total", "counter",
             "Prefixes exported off draining replicas to their new "
             "rendezvous owners", [("", c.get("drain_sweeps", 0))])


def _autoscaler_metrics(w: _Writer, ctl) -> None:
    """Elasticity controller accounting: every decision — applied,
    errored, or refused by a hysteresis gate — is a counted outcome."""
    totals = dict(ctl.counters()["actions_total"])
    # Pre-seed the cells dashboards alert on, keep any others.
    seeds = [(role, direction, outcome)
             for role in ("prefill", "decode", "unified")
             for direction in ("up", "down")
             for outcome in ("applied", "refused_cooldown", "refused_dwell")]
    for key in seeds:
        totals.setdefault(key, 0)
    w.metric("autoscale_actions_total", "counter",
             "Autoscale decisions by role, direction (up/down/rebalance) "
             "and outcome (applied, error, or the refusing gate)",
             [(f'{{role="{r}",direction="{d}",outcome="{o}"}}', n)
              for (r, d, o), n in sorted(totals.items())])
    w.metric("autoscale_breaker_open", "gauge",
             "1 while the controller's executor breaker is open "
             "(decisions refused, not retried)",
             [("", 1 if ctl.breaker.state == "open" else 0)])


def _remediation_metrics(w: _Writer, rem) -> None:
    """Closed-loop remediation accounting: every plan outcome (including
    every refusing gate), per-verb breaker state, and verification
    results — the observe-only default still counts ``proposed``."""
    from k8s_llm_monitor_tpu.remediation.executor import (
        OUTCOMES,
        VERIFY_RESULTS,
    )
    from k8s_llm_monitor_tpu.remediation.plans import PLAN_VERBS

    c = rem.counters()
    plans = dict(c["plans_total"])
    for verb in PLAN_VERBS:
        for outcome in OUTCOMES:
            plans.setdefault((verb, outcome), 0)
    w.metric("remediation_plans_total", "counter",
             "Action plans by verb and outcome (proposed, executed, error, "
             "or the refusing gate: approval/breaker/rate/replay)",
             [(f'{{verb="{v}",outcome="{o}"}}', n)
              for (v, o), n in sorted(plans.items())])
    w.metric("remediation_breaker_open", "gauge",
             "1 while the verb's executor circuit breaker is open "
             "(plans refused, not retried)",
             [(f'{{verb="{v}"}}', open_)
              for v, open_ in sorted(c["breaker_open"].items())])
    verify = dict(c["verify_total"])
    for result in VERIFY_RESULTS:
        verify.setdefault(result, 0)
    w.metric("remediation_verify_total", "counter",
             "Post-action verification turns by result (resolved = "
             "condition cleared AND the verdict is non-critical)",
             [(f'{{result="{r}"}}', n) for r, n in sorted(verify.items())])


def _diagnosis_metrics(w: _Writer, pipeline, backend) -> None:
    """Standing diagnosis pipeline (PR 6): verdict counts by severity,
    trigger→verdict lag, and the constrained-decode tax on the engine."""
    if pipeline is not None:
        counts = pipeline.store.counts()
        w.metric("diagnosis_verdicts_total", "counter",
                 "Verdicts published by the diagnosis pipeline, by severity",
                 [(f'{{severity="{s}"}}', counts.get(s, 0))
                  for s in pipeline.store.SEVERITIES])
        w.metric("diagnosis_pipeline_lag_ms", "gauge",
                 "Burst trigger to published verdict latency "
                 "(most recent verdict)",
                 [("", round(pipeline.store.lag_ms(), 3))])
        w.metric("diagnosis_triggers_total", "counter",
                 "Warning-event bursts that fired the pipeline",
                 [("", pipeline.triggers_total)])
        w.metric("diagnosis_queries_total", "counter",
                 "Root-cause LLM queries the pipeline ran",
                 [("", pipeline.queries_total)])
        w.metric("diagnosis_errors_total", "counter",
                 "Pipeline diagnosis attempts that raised",
                 [("", pipeline.errors_total)])
        w.metric("diagnosis_context_events", "gauge",
                 "Cluster events held in the context ring buffer",
                 [("", len(pipeline.context))])
    # Emitted UNCONDITIONALLY: the fleet router proxies replica /metrics,
    # and a gauge that only the local-engine backend emits would silently
    # mix populations across a scrape of mixed backends.  Backends that do
    # not track the EMA (remote/openai/template, or a router with no
    # engine) emit an explicit NaN marker instead of being absent, so
    # dashboards can tell "not measured here" from "never scraped".
    overhead = getattr(backend, "constrained_decode_overhead_ms", None)
    w.metric("constrained_decode_overhead_ms", "gauge",
             "Per-token decode cost of FSM-constrained sampling vs "
             "free decoding (EMA delta; 0 until both paths observed; "
             "NaN when this backend does not measure it)",
             [("", round(overhead, 4) if overhead is not None
               else float("nan"))])


def _device_metrics(w: _Writer) -> None:
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend available
        return
    samples_used, samples_total = [], []
    for d in devices:
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:  # noqa: BLE001 — backend without memory_stats
            pass
        label = f'{{device="{d.id}",kind="{d.device_kind}"}}'
        if "bytes_in_use" in stats:
            samples_used.append((label, stats["bytes_in_use"]))
        if "bytes_limit" in stats:
            samples_total.append((label, stats["bytes_limit"]))
    if samples_used:
        w.metric("device_memory_used_bytes", "gauge",
                 "Accelerator (HBM) bytes in use", samples_used)
    if samples_total:
        w.metric("device_memory_limit_bytes", "gauge",
                 "Accelerator (HBM) byte limit", samples_total)
    w.metric("devices", "gauge", "Visible accelerator devices",
             [("", len(devices))])


def _telemetry_metrics(w: _Writer, scraper) -> None:
    """Signal-scraper self-accounting (the telemetry plane watching
    itself): scrape cadence health and store occupancy."""
    c = scraper.counters()
    w.metric("telemetry_scrapes_total", "counter",
             "Signal-scraper sampling passes completed",
             [("", c["scrapes_total"])])
    w.metric("telemetry_scrape_errors_total", "counter",
             "Signal-scraper passes that raised and were dropped",
             [("", c["scrape_errors_total"])])
    w.metric("telemetry_anomalies_total", "counter",
             "Anomaly flags raised by the derived-signal layer "
             "(edge-triggered, per target+flag cooldown)",
             [("", c["anomalies_total"])])
    w.metric("telemetry_evicted_targets_total", "counter",
             "Departed fleet targets whose series were GC'd from the "
             "store (membership-lifecycle probe-leak cleanup)",
             [("", c.get("evicted_targets_total", 0))])
    t = scraper.store.totals()
    w.metric("telemetry_series", "gauge",
             "Live time series held by the in-process store",
             [("", t["series"])])
    w.metric("telemetry_points_total", "counter",
             "Points recorded into the time-series store",
             [("", t["points_total"])])
    w.metric("telemetry_dropped_series_total", "counter",
             "Series refused because the store hit max_series",
             [("", t["dropped_series_total"])])


def _tenant_metrics(w: _Writer, srv) -> None:
    """Per-tenant admission/quota/KV families (resilience/tenancy.py).

    Cardinality discipline: the ``tenant`` label is capped at the top-K
    tenants by admitted requests plus ONE aggregate ``other`` bucket
    (always emitted, 0 when nothing spilled), so an abusive client
    minting fresh tenant ids can grow the scrape by exactly nothing.
    K comes from ``config.tenancy.top_k_metrics``.
    """
    gov = getattr(srv, "governor", None)
    if gov is None:
        return
    snap = gov.snapshot()
    tcfg = getattr(getattr(srv, "config", None), "tenancy", None)
    top_k = max(1, int(getattr(tcfg, "top_k_metrics", 8) or 8))
    # Device-resident prefix-cache blocks join on the same label set.
    blocks: dict[str, int] = {}
    svc = srv.engine_service() if hasattr(srv, "engine_service") else None
    engine = getattr(svc, "engine", None)
    tier_fn = getattr(engine, "kv_tier_stats", None)
    if callable(tier_fn):
        blocks = dict(tier_fn().get("tenant_blocks") or {})
    ranked = sorted(snap, key=lambda t: (-snap[t]["admitted"], t))
    shown = ranked[:top_k]
    spilled = ranked[top_k:]
    kv_spilled = [t for t in blocks if t not in shown]

    def rows(per_tenant, other_value):
        return ([(f'{{tenant="{t}"}}', per_tenant(t)) for t in shown]
                + [('{tenant="other"}', other_value)])

    w.metric("tenant_requests_total", "counter",
             "Requests admitted per tenant (top-K by volume; the rest "
             "aggregate under tenant=\"other\")",
             rows(lambda t: snap[t]["admitted"],
                  sum(snap[t]["admitted"] for t in spilled)))
    w.metric("tenant_shed_total", "counter",
             "Refusals charged per tenant: quota 429s plus SLO-class "
             "sheds downstream of admission",
             rows(lambda t: snap[t]["sheds"],
                  sum(snap[t]["sheds"] for t in spilled)))
    w.metric("tenant_kv_blocks", "gauge",
             "Distinct device prefix-cache blocks resident per tenant "
             "namespace (the fairness cap's accounting)",
             rows(lambda t: blocks.get(t, 0),
                  sum(blocks[t] for t in kv_spilled)))
    w.metric("tenant_quota_remaining", "gauge",
             "Token-quota bucket level per tenant (-1 = unlimited; NaN "
             "for the aggregate bucket — levels do not sum)",
             rows(lambda t: snap[t]["quota_remaining"], float("nan")))


def _tracing_metrics(w: _Writer) -> None:
    """Tracer + flight-recorder self-accounting."""
    from k8s_llm_monitor_tpu.observability.flight import get_flight_recorder
    from k8s_llm_monitor_tpu.observability.tracing import get_tracer

    tracer = get_tracer()
    w.metric("trace_sample_rate", "gauge",
             "Configured head-sampling rate (K8SLLM_TRACE_SAMPLE)",
             [("", tracer.sample)])
    w.metric("trace_spans_recorded_total", "counter",
             "Spans pushed to the in-process ring",
             [("", tracer.recorded)])
    rec = get_flight_recorder()
    w.metric("flight_dumps_total", "counter",
             "Flight-recorder artifacts written on failure edges",
             [("", rec.dumps)])
    w.metric("flight_dump_errors_total", "counter",
             "Flight-recorder dump attempts that hit an OSError",
             [("", rec.dump_errors)])


def render_prometheus(srv: "MonitorServer", openmetrics: bool = False) -> str:
    w = _Writer(openmetrics=openmetrics)
    w.metric("build_info", "gauge", "Monitor build info",
             [('{version="1.0.0"}', 1)])
    engine = None
    service = None
    if srv.analysis is not None:
        backend = getattr(srv.analysis, "backend", None)
        engine = getattr(backend, "engine", None)
        service = getattr(backend, "service", None)
    if engine is not None:
        _engine_metrics(w, engine)
        _latency_histograms(w, engine)
        _resilience_metrics(w, engine, service)
    supervisor = srv.engine_supervisor() if hasattr(
        srv, "engine_supervisor") else None
    if supervisor is not None:
        _lifecycle_metrics(w, supervisor)
    breaker = getattr(getattr(srv.client, "backend", None), "breaker", None)
    if breaker is not None:
        _kube_breaker_metrics(w, breaker)
    router = getattr(srv.analysis, "router", None)
    if router is not None:
        _fleet_metrics(w, router)
    autoscaler = getattr(srv, "autoscaler", None)
    if autoscaler is not None:
        _autoscaler_metrics(w, autoscaler)
    remediation = getattr(srv, "remediation", None)
    if remediation is not None:
        _remediation_metrics(w, remediation)
    if srv.manager is not None:
        _manager_metrics(w, srv.manager)
    backend = getattr(srv.analysis, "backend", None)
    pipeline = getattr(srv, "diagnosis", None)
    if pipeline is not None or backend is not None:
        _diagnosis_metrics(w, pipeline, backend)
    scraper = getattr(srv, "signals", None)
    if scraper is not None:
        _telemetry_metrics(w, scraper)
    _tenant_metrics(w, srv)
    _tracing_metrics(w)
    _device_metrics(w)
    # Render-time self-lint: a malformed family poisons the whole scrape
    # silently (Prometheus drops what it can't parse), so the exporter
    # counts its own format errors as a scrapeable metric.  The lint
    # family is appended after linting; it uses the same writer path that
    # every linted family went through.
    errors = lint_exposition("\n".join(w.lines) + "\n")
    if errors:  # pragma: no cover — a clean exporter never logs here
        import logging

        logging.getLogger("monitor.exporter").warning(
            "exposition lint: %s", "; ".join(errors[:5]))
    w.metric("exposition_lint_errors", "gauge",
             "Format errors the exporter found in its own output "
             "(0 = clean scrape)", [("", len(errors))])
    return w.render()
