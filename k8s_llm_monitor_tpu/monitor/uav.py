"""UAV telemetry state model + MAVLink-style simulator.

Parity target: ``/root/reference/pkg/uav/mavlink_simulator.go`` — the
UAVState tree (:11-106 — GPS/Attitude/Flight/Battery/Mission/Health),
initial state (:118-176), the 10 Hz update loop (:248-262), circular AUTO
flight path + attitude wobble (:272-297), battery discharge with
voltage/temperature coupling and time-remaining estimate (:311-329),
WARNING <20% / CRITICAL <10% transitions (:336-347), the bounded message
ring (:350-352), and the command set (Arm requires a 3D GPS fix, :224).
"""

from __future__ import annotations

import math
import random
import threading
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any

from k8s_llm_monitor_tpu.devtools.lockcheck import make_lock
from k8s_llm_monitor_tpu.monitor.models import to_jsonable, utcnow

UPDATE_RATE_HZ = 10.0  # ref mavlink_simulator.go:172
CENTER_LAT = 39.9042
CENTER_LON = 116.4074


@dataclass
class GPSData:
    latitude: float = 0.0
    longitude: float = 0.0
    altitude: float = 0.0
    relative_altitude: float = 0.0
    hdop: float = 0.0
    satellite_count: int = 0
    fix_type: int = 0  # 0=none, 2=2D, 3=3D
    ground_speed: float = 0.0
    course_over_ground: float = 0.0
    timestamp: datetime = field(default_factory=utcnow)


@dataclass
class AttitudeData:
    roll: float = 0.0
    pitch: float = 0.0
    yaw: float = 0.0
    roll_rate: float = 0.0
    pitch_rate: float = 0.0
    yaw_rate: float = 0.0
    timestamp: datetime = field(default_factory=utcnow)


@dataclass
class FlightData:
    mode: str = "STABILIZE"  # MANUAL STABILIZE LOITER AUTO RTL LAND
    armed: bool = False
    airspeed: float = 0.0
    ground_speed: float = 0.0
    vertical_speed: float = 0.0
    throttle_percent: float = 0.0
    timestamp: datetime = field(default_factory=utcnow)


@dataclass
class BatteryData:
    voltage: float = 0.0
    current: float = 0.0
    remaining_percent: float = 100.0
    remaining_capacity: float = 0.0  # mAh
    total_capacity: float = 0.0  # mAh
    temperature: float = 0.0  # °C
    cell_count: int = 0
    time_remaining: int = 0  # s
    timestamp: datetime = field(default_factory=utcnow)


@dataclass
class MissionData:
    current_waypoint: int = 0
    total_waypoints: int = 0
    mission_state: str = "IDLE"  # IDLE ACTIVE PAUSED COMPLETED
    distance_to_wp: float = 0.0
    eta_to_wp: int = 0
    timestamp: datetime = field(default_factory=utcnow)


@dataclass
class HealthData:
    system_status: str = "OK"  # OK WARNING CRITICAL ERROR
    sensors_health: dict[str, bool] = field(default_factory=dict)
    error_count: int = 0
    warning_count: int = 0
    messages: list[str] = field(default_factory=list)
    last_heartbeat: datetime = field(default_factory=utcnow)
    timestamp: datetime = field(default_factory=utcnow)


@dataclass
class UAVState:
    uav_id: str = ""
    node_name: str = ""
    system_time: datetime = field(default_factory=utcnow)
    gps: GPSData = field(default_factory=GPSData)
    attitude: AttitudeData = field(default_factory=AttitudeData)
    flight: FlightData = field(default_factory=FlightData)
    battery: BatteryData = field(default_factory=BatteryData)
    mission: MissionData = field(default_factory=MissionData)
    health: HealthData = field(default_factory=HealthData)

    def to_dict(self) -> dict[str, Any]:
        return to_jsonable(self)


MAX_HEALTH_MESSAGES = 10


class MAVLinkSimulator:
    """Simulated flight controller ticking at 10 Hz on a daemon thread."""

    def __init__(self, uav_id: str, node_name: str, seed: int | None = None) -> None:
        rng = random.Random(seed)
        self._rng = rng
        self._state = UAVState(
            uav_id=uav_id,
            node_name=node_name,
            gps=GPSData(
                latitude=CENTER_LAT + rng.random() * 0.01,
                longitude=CENTER_LON + rng.random() * 0.01,
                altitude=50.0,
                fix_type=3,
                satellite_count=12,
                hdop=1.0,
            ),
            battery=BatteryData(
                voltage=22.2,  # 6S pack
                current=0.5,  # idle draw
                remaining_percent=100.0,
                remaining_capacity=5000.0,
                total_capacity=5000.0,
                temperature=25.0,
                cell_count=6,
            ),
            health=HealthData(
                sensors_health={
                    "gps": True,
                    "compass": True,
                    "accelerometer": True,
                    "gyroscope": True,
                    "barometer": True,
                    "battery": True,
                }
            ),
        )
        self._lock = make_lock("uav.sim", reentrant=True)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._elapsed = 0.0
        self.update_period = 1.0 / UPDATE_RATE_HZ

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"uav-sim-{self._state.uav_id}", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _loop(self) -> None:
        while not self._stop.wait(self.update_period):
            self.tick(self.update_period)

    # -- state access ----------------------------------------------------------

    def get_state(self) -> dict[str, Any]:
        """JSON-shaped deep copy of the current state (thread-safe)."""
        with self._lock:
            return self._state.to_dict()

    # -- commands (ref :214-245, :358-388) --------------------------------------

    def set_flight_mode(self, mode: str) -> None:
        with self._lock:
            self._state.flight.mode = mode
            self._message(f"Flight mode changed to: {mode}")

    def arm(self) -> bool:
        with self._lock:
            if self._state.gps.fix_type < 3:
                return False  # needs a 3D fix
            self._state.flight.armed = True
            self._message("Armed")
            return True

    def disarm(self) -> None:
        with self._lock:
            self._state.flight.armed = False
            self._message("Disarmed")

    def take_off(self, altitude: float = 50.0) -> bool:
        with self._lock:
            if not self._state.flight.armed:
                return False
            self._state.flight.mode = "AUTO"
            self._state.mission.mission_state = "ACTIVE"
            self._message(f"Taking off to altitude: {altitude:.0f}m")
            return True

    def land(self) -> None:
        with self._lock:
            self._state.flight.mode = "LAND"
            self._message("Landing initiated")

    def return_to_launch(self) -> None:
        with self._lock:
            self._state.flight.mode = "RTL"
            self._message("Returning to launch")

    def _message(self, msg: str) -> None:
        msgs = self._state.health.messages
        msgs.append(msg)
        if len(msgs) > MAX_HEALTH_MESSAGES:
            del msgs[:-MAX_HEALTH_MESSAGES]

    # -- dynamics (ref :272-352) ------------------------------------------------

    def tick(self, dt: float | None = None) -> None:
        """Advance the simulation one step. Exposed for deterministic tests
        (the thread loop calls it at 10 Hz)."""
        dt = dt if dt is not None else self.update_period
        rng = self._rng
        with self._lock:
            self._elapsed += dt
            t = self._elapsed
            s = self._state
            now = utcnow()

            # GPS: circular flight path in armed AUTO mode
            if s.flight.armed and s.flight.mode == "AUTO":
                radius = 0.001  # ~100 m
                omega = 0.1  # rad/s
                s.gps.latitude = CENTER_LAT + radius * math.cos(omega * t)
                s.gps.longitude = CENTER_LON + radius * math.sin(omega * t)
                s.gps.relative_altitude = 50.0 + 10.0 * math.sin(0.05 * t)
                s.gps.ground_speed = 5.0 + rng.random() * 0.5
                s.gps.course_over_ground = (omega * t * 180.0 / math.pi) % 360.0
            s.gps.timestamp = now

            # attitude wobble while armed
            if s.flight.armed:
                s.attitude.roll = 5.0 * math.sin(0.5 * t) + rng.random() * 0.5
                s.attitude.pitch = 3.0 * math.cos(0.3 * t) + rng.random() * 0.3
                s.attitude.yaw = s.gps.course_over_ground % 360.0
                s.attitude.roll_rate = rng.random() * 2.0 - 1.0
                s.attitude.pitch_rate = rng.random() * 2.0 - 1.0
                s.attitude.yaw_rate = rng.random() * 5.0 - 2.5
            s.attitude.timestamp = now

            # flight data
            if s.flight.armed:
                s.flight.airspeed = s.gps.ground_speed + rng.random() * 0.5
                s.flight.ground_speed = s.gps.ground_speed
                s.flight.vertical_speed = math.cos(0.05 * t) * 2.0
                s.flight.throttle_percent = 50.0 + 20.0 * math.sin(0.1 * t)
            else:
                s.flight.throttle_percent = 0.0
                s.flight.vertical_speed = 0.0
            s.flight.timestamp = now

            # battery: ~0.1%/s discharge while armed, with voltage sag and
            # temperature rise coupled to depth of discharge
            if s.flight.armed:
                s.battery.remaining_percent = max(
                    0.0, s.battery.remaining_percent - 0.1 * dt
                )
                s.battery.remaining_capacity = (
                    s.battery.total_capacity * s.battery.remaining_percent / 100.0
                )
                s.battery.current = 10.0 + s.flight.throttle_percent * 0.2
                s.battery.voltage = 22.2 - (100.0 - s.battery.remaining_percent) * 0.04
                s.battery.temperature = (
                    25.0 + (100.0 - s.battery.remaining_percent) * 0.3
                )
                if s.battery.current > 0:
                    s.battery.time_remaining = int(
                        s.battery.remaining_capacity / s.battery.current * 3600 / 1000
                    )
            s.battery.timestamp = now

            # health transitions
            s.health.last_heartbeat = now
            s.health.timestamp = now
            if s.battery.remaining_percent < 10.0:
                if s.health.system_status != "CRITICAL":
                    s.health.system_status = "CRITICAL"
                    s.health.error_count += 1
                    self._message("Critical battery level - RTL recommended")
            elif s.battery.remaining_percent < 20.0 and s.health.system_status == "OK":
                s.health.system_status = "WARNING"
                s.health.warning_count += 1
                self._message("Low battery warning")

            s.system_time = now

    # -- test helpers -----------------------------------------------------------

    def set_battery_percent(self, pct: float) -> None:
        with self._lock:
            self._state.battery.remaining_percent = pct
            self._state.battery.remaining_capacity = (
                self._state.battery.total_capacity * pct / 100.0
            )
