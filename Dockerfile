# Monitor server image (parity: /root/reference/Dockerfile — multi-stage,
# non-root, HEALTHCHECK; the runtime here is Python+JAX instead of a Go
# binary, and for TPU serving the image expects the libtpu wheel to be
# present on the TPU VM host or installed in a deploy-specific layer).
FROM python:3.12-slim AS base

WORKDIR /app
RUN useradd --create-home --uid 10001 monitor \
    && apt-get update && apt-get install -y --no-install-recommends curl \
    && rm -rf /var/lib/apt/lists/*

# Core deps; "jax[tpu]" replaces "jax" on TPU VMs.
RUN pip install --no-cache-dir jax flax optax orbax-checkpoint einops \
    numpy pyyaml transformers safetensors

COPY k8s_llm_monitor_tpu/ k8s_llm_monitor_tpu/
COPY web/ web/

USER monitor
EXPOSE 8081
HEALTHCHECK --interval=30s --timeout=5s --start-period=30s \
  CMD curl -sf http://localhost:8081/health || exit 1

ENTRYPOINT ["python", "-m", "k8s_llm_monitor_tpu.cmd.server"]
CMD ["--host", "0.0.0.0", "--port", "8081", "--cluster", "kube"]
