#!/usr/bin/env python
"""Single-chip serving benchmark — the north-star SLO tracker.

Measures p50 TTFT for a burst of concurrent diagnosis-sized queries through
the continuous-batching engine, decode throughput, and achieved MXU / HBM
utilization, and prints ONE JSON line:

    {"metric": "p50_ttft_100c_ms", "value": <ms>, "unit": "ms",
     "vs_baseline": <500ms / p50>, ...}

``vs_baseline`` is measured against the north-star SLO (p50 TTFT < 500 ms,
BASELINE.md / BASELINE.json north_star) since the reference publishes no
benchmark numbers of its own (verified in SURVEY.md §6): > 1.0 beats the SLO.

Model: **Llama-3-8B geometry with int8 weight-only quantization**
(utils/quantize.py) — the real BASELINE.md config #2/#4 target, which bf16
cannot fit on the 16 GB chip.  Weights are random-init (generated directly
in int8; the bf16 intermediate would not fit either) — the arithmetic,
shapes, and HBM traffic match the real checkpoint exactly.  Honest context:
the 500 ms SLO is defined for v5e-8 (8 chips, BASELINE.md config #4); this
bench drives ONE chip with the full 100-request burst, i.e. 8x the SLO's
per-chip load.  When more than one device is visible, the **mesh leg**
(``mesh_leg``) runs ONE tensor-parallel engine over all of them and reports
measured ``mesh_p50_ttft_ms`` / ``mesh_p99_ttft_ms`` / ``mesh_tok_s`` — the
apples-to-apples multi-chip numbers.  The old per-chip-equivalent leg
(100/8 -> 12 concurrent through one chip) remains in extras but is
informational only.  ``BENCH_MESH_ONLY=1`` (``make bench-mesh``) runs just
the mesh leg; off-TPU it executes on the forced-host-device mesh and is
flagged ``mesh_dryrun``.

A persistent XLA compilation cache (.jax_cache/) makes warm boots cheap;
the bench reports its warmup time and whether the cache was already
populated.

Run: ``python bench.py`` (uses the default JAX platform — the real TPU under
the driver; set BENCH_MODEL=llama-1b BENCH_CONCURRENCY=8 JAX_PLATFORMS=cpu
to shrink for local smoke runs).
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import time

CACHE_DIR = pathlib.Path(__file__).parent / ".jax_cache"

# Single-tenant legs tag KV migrations with the default namespace
# explicitly (the tenant-namespace lint requires the kwarg everywhere).
# Pure-Python import: pulls no jax, so --help stays fast.
from k8s_llm_monitor_tpu.resilience.tenancy import (  # noqa: E402
    DEFAULT_TENANT as TEN,
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bucket64(n: int) -> int:
    """Round ``n`` up to the 64-token prefill grid via the engine's own
    bucket rounding (serving.engine.prefill_bucket_for), so the bench's
    engine-sizing math can never drift from the admission path's."""
    from k8s_llm_monitor_tpu.serving.engine import prefill_bucket_for

    n = max(int(n), 1)
    ladder = tuple(64 * i for i in range(1, (n + 63) // 64 + 1))
    return prefill_bucket_for(n, ladder)


# Approximate chip peaks for utilization reporting, keyed by substrings of
# jax Device.device_kind.  (bf16 matmul TFLOP/s, HBM GB/s.)
CHIP_PEAKS = {
    "v5 lite": (197e12, 819e9),     # v5e
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v6": (918e12, 1640e9),         # v6e (Trillium)
}


def chip_peaks(device_kind: str) -> tuple[float, float]:
    kind = device_kind.lower()
    for key, peaks in CHIP_PEAKS.items():
        if key in kind:
            return peaks
    return (0.0, 0.0)


def weight_accounting(params, tied: bool) -> tuple[int, int]:
    """(matmul weight elements, streamed weight bytes per decode step).

    The untied embedding table is a pure gather — zero matmul FLOPs and
    only B rows of traffic per step — so it is excluded from both unless
    the model ties it to the unembed matmul.
    """
    import jax

    elems = 0
    stream_bytes = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        keys = [p.key for p in path if hasattr(p, "key")]
        if not keys:
            continue
        is_embed = "embed" in keys
        if keys[-1] in ("kernel", "kernel_q", "weight", "weight_q"):
            if is_embed and not tied:
                continue
            elems += leaf.size
            stream_bytes += leaf.size * leaf.dtype.itemsize
    return elems, stream_bytes


def fleet_leg(cfg, params) -> dict:
    """Fleet tier (fleet/router.py): 1 vs 2 in-process replicas behind the
    router — aggregate throughput and the per-request completion-latency
    tail, then the same 2-replica burst with hedged dispatch on.  The
    replicas share ``params`` (no extra weight copies); each gets its own
    small KV pool."""
    import numpy as np

    from k8s_llm_monitor_tpu.fleet import (
        FleetRouter,
        HedgeConfig,
        LocalReplica,
        ReplicaRegistry,
    )
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.serving.service import EngineService

    rng = np.random.default_rng(7)
    f_len = int(os.environ.get("BENCH_FLEET_PROMPT_LEN", "64"))
    f_gen = int(os.environ.get("BENCH_FLEET_MAX_TOKENS", "32"))
    f_n = int(os.environ.get("BENCH_FLEET_CONCURRENCY", "16"))
    f_cap = f_len + f_gen + 16
    f_ecfg = EngineConfig(
        max_slots=8,
        num_blocks=8 * ((f_cap + 15) // 16) + 16,
        block_size=16,
        max_blocks_per_seq=(f_cap + 15) // 16,
        prefill_buckets=(f_len,),
        max_prefills_per_step=8,
        decode_steps_per_iter=4,
    )

    def f_prompt() -> list[int]:
        return [int(t) for t in
                rng.integers(4, cfg.vocab_size - 4, size=f_len)]

    def fleet_run(n_reps: int, hedge=None):
        reps = [
            LocalReplica(
                f"bench-r{i}",
                service=EngineService(
                    InferenceEngine(cfg, params, f_ecfg, eos_id=-1)))
            for i in range(n_reps)
        ]
        reg = ReplicaRegistry()
        for r in reps:
            reg.add(r)
        reg.refresh()
        router = FleetRouter(reg, policy="affinity", hedge=hedge)
        try:
            t_start = time.monotonic()
            flights = [(time.monotonic(),
                        router.submit(f_prompt(),
                                      SamplingParams(max_tokens=f_gen)))
                       for _ in range(f_n)]
            lat = []
            for t_sub, h in flights:
                res = h.result(timeout=600.0)
                assert res.finish_reason == "length", res.error
                lat.append(time.monotonic() - t_sub)
            wall = time.monotonic() - t_start
        finally:
            for r in reps:
                r.close()
        p99_ms = float(np.percentile(np.array(sorted(lat)), 99)) * 1e3
        return f_n * f_gen / wall, p99_ms, router.counters()

    one_tok_s, _, _ = fleet_run(1)
    log(f"fleet: 1 replica {one_tok_s:.1f} tok/s "
        f"({f_n} concurrent, gen {f_gen})")
    two_tok_s, unhedged_p99_ms, c2 = fleet_run(2)
    log(f"fleet: 2 replicas {two_tok_s:.1f} tok/s, unhedged p99 "
        f"completion {unhedged_p99_ms:.0f} ms "
        f"(affinity hits {c2['affinity_hits']}, "
        f"spills {c2['affinity_spills']})")
    _, hedged_p99_ms, ch = fleet_run(2, hedge=HedgeConfig(enabled=True))
    log(f"fleet: 2 replicas hedged p99 completion {hedged_p99_ms:.0f} ms "
        f"({ch['hedges_fired']} hedges fired, {ch['hedges_won']} won)")
    return {
        "fleet_1replica_tok_s": round(one_tok_s, 1),
        "fleet_2replica_tok_s": round(two_tok_s, 1),
        "fleet_unhedged_p99_completion_ms": round(unhedged_p99_ms, 1),
        "fleet_hedged_p99_completion_ms": round(hedged_p99_ms, 1),
        "fleet_hedges_fired": ch["hedges_fired"],
        "fleet_hedges_won": ch["hedges_won"],
        "fleet_affinity_hits": c2["affinity_hits"],
        "fleet_affinity_spills": c2["affinity_spills"],
        "fleet_concurrency": f_n,
    }


def tenant_fairness_leg(cfg, params) -> dict:
    """Multi-tenant fairness (resilience/tenancy.py): a Zipf-weighted
    population of quiet tenants with mixed SLO classes shares one engine
    with a flooding tenant submitting 10x its request quota, under seeded
    ``lane_eviction`` faults.  Gates (hard — a fairness regression IS a
    bench failure):

      * every flood refusal is a tenant-tagged 429 naming the flooder;
      * no quiet tenant is ever quota-refused or shed;
      * quiet interactive p99 TTFT stays <= 2x the solo (flood-free)
        baseline of the identical burst;
      * zero lost tokens: the governor's settled charge equals the tokens
        each tenant's streams actually delivered;
      * byte-exact: every quiet stream reproduces its solo-baseline
        output despite the faults and the contention.
    """
    import numpy as np

    from k8s_llm_monitor_tpu.resilience.errors import OverloadedError
    from k8s_llm_monitor_tpu.resilience.faults import get_injector
    from k8s_llm_monitor_tpu.resilience.tenancy import TenantGovernor
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.serving.service import EngineService

    rng = np.random.default_rng(17)
    t_len = int(os.environ.get("BENCH_TENANT_PROMPT_LEN", "64"))
    t_gen = int(os.environ.get("BENCH_TENANT_MAX_TOKENS", "16"))
    t_n = int(os.environ.get("BENCH_TENANT_CONCURRENCY", "24"))
    ttft_budget = float(os.environ.get("BENCH_TENANT_TTFT_BUDGET", "2.0"))
    t_cap = t_len + t_gen + 16
    t_ecfg = EngineConfig(
        max_slots=8,
        num_blocks=8 * ((t_cap + 15) // 16) + 16,
        block_size=16,
        max_blocks_per_seq=(t_cap + 15) // 16,
        prefill_buckets=(t_len,),
        max_prefills_per_step=8,
        decode_steps_per_iter=4,
    )

    # Zipf-weighted quiet tenants (rank-r tenant gets ~1/r of the load)
    # with SLO classes round-robined across the burst; prompts are fixed
    # up front so the contended run must reproduce the solo bytes.
    quiet = ("team-a", "team-b", "team-c", "team-d")
    zipf = np.array([1.0 / (r + 1) for r in range(len(quiet))])
    zipf /= zipf.sum()
    classes = ("interactive", "standard", "batch")
    plan = []
    for i in range(t_n):
        plan.append((
            quiet[int(rng.choice(len(quiet), p=zipf))],
            classes[i % len(classes)],
            [int(t) for t in rng.integers(4, cfg.vocab_size - 4,
                                          size=t_len)],
        ))
    per_tenant = {t: sum(1 for ten, _, _ in plan if ten == t)
                  for t in quiet}
    # Quota sized so every quiet tenant fits with headroom and the
    # flooder's 10x burst mostly does not.
    req_burst = float(max(per_tenant.values()) + 2)
    flood_n = int(10 * req_burst)

    def run_burst(svc, *, flood: bool):
        flood_429 = 0
        flood_handles = []
        if flood:
            for j in range(flood_n):
                p = [int(t) for t in rng.integers(4, cfg.vocab_size - 4,
                                                  size=t_len)]
                try:
                    flood_handles.append(svc.submit(
                        p, SamplingParams(max_tokens=t_gen),
                        request_id=f"flood-{j}", tenant="flood",
                        slo_class="batch"))
                except OverloadedError as exc:
                    assert exc.tenant == "flood", \
                        "flood refusal not tagged with the flooder"
                    assert exc.retriable and exc.retry_after_s > 0
                    flood_429 += 1
        handles = [(ten, c, svc.submit(
            list(p), SamplingParams(max_tokens=t_gen),
            request_id=f"q{i}-{'c' if flood else 's'}", tenant=ten,
            slo_class=c)) for i, (ten, c, p) in enumerate(plan)]
        results = []
        for ten, c, h in handles:
            res = h.result(timeout=600.0)
            assert res.finish_reason == "length", (ten, res.error)
            assert len(res.token_ids) == t_gen, "lost tokens"
            results.append((ten, c, res))
        flood_delivered = 0
        for h in flood_handles:
            res = h.result(timeout=600.0)
            if res.finish_reason == "length":
                flood_delivered += len(res.token_ids)
        return results, flood_429, len(flood_handles), flood_delivered

    def p99_interactive(results):
        ttfts = sorted(r.ttft_s for _, c, r in results
                       if c == "interactive")
        return float(np.percentile(np.array(ttfts), 99))

    # Solo baseline: the identical quiet burst, no flood, no faults.
    svc = EngineService(InferenceEngine(cfg, params, t_ecfg, eos_id=-1))
    try:
        base, _, _, _ = run_burst(svc, flood=False)
    finally:
        svc.stop(timeout=30)
    solo_p99 = p99_interactive(base)
    log(f"tenant: solo baseline interactive p99 TTFT "
        f"{solo_p99 * 1e3:.1f} ms ({t_n} quiet reqs over {len(quiet)} "
        f"Zipf tenants)")

    gov = TenantGovernor(requests_per_s=0.5, request_burst=req_burst,
                         tokens_per_s=float(t_gen),
                         token_burst=req_burst * t_gen * 4.0)
    svc = EngineService(InferenceEngine(cfg, params, t_ecfg, eos_id=-1),
                        governor=gov)
    get_injector().reset(seed=4321)
    get_injector().arm("lane_eviction", rate=0.1, times=3)
    try:
        contended, flood_429, flood_ok, flood_delivered = run_burst(
            svc, flood=True)
    finally:
        svc.stop(timeout=30)
        get_injector().reset()

    # The flooder was rate-limited (10x quota: most submissions refused)
    # and within-quota tenants never felt it.
    assert flood_429 > 0, "flood was never rate-limited"
    snap = gov.snapshot()
    assert snap["flood"]["quota_refusals"] == flood_429
    for t in quiet:
        assert snap[t]["quota_refusals"] == 0, f"{t} was quota-refused"
        assert snap[t]["sheds"] == 0, f"{t} was shed by the flood"

    # Byte-exact under faults + contention, and charged == delivered.
    delivered = {t: 0 for t in quiet}
    for (ten, _, solo_r), (ten2, _, cont_r) in zip(base, contended):
        assert ten == ten2
        assert cont_r.token_ids == solo_r.token_ids, \
            f"{ten}: contended output diverged from solo baseline"
        delivered[ten] += len(cont_r.token_ids)
    deadline = time.monotonic() + 10.0
    while (any(v["inflight"] for v in gov.snapshot().values())
           and time.monotonic() < deadline):
        time.sleep(0.05)
    for t in quiet:
        assert gov.charged_tokens(t) == delivered[t], \
            f"{t}: charged {gov.charged_tokens(t)} != delivered"
    assert gov.charged_tokens("flood") == flood_delivered

    cont_p99 = p99_interactive(contended)
    ratio = cont_p99 / max(solo_p99, 1e-9)
    log(f"tenant: contended interactive p99 TTFT {cont_p99 * 1e3:.1f} ms "
        f"= {ratio:.2f}x solo ({flood_429}/{flood_n} flood reqs 429'd, "
        f"{flood_ok} admitted, {get_injector().fired('lane_eviction')} "
        f"lane_eviction faults fired)")
    assert ratio <= ttft_budget, (
        f"flood degraded quiet interactive p99 TTFT {ratio:.2f}x "
        f"(budget {ttft_budget}x)")
    return {
        "tenant_interactive_p99_ttft_ratio": round(ratio, 3),
        "tenant_solo_p99_ttft_ms": round(solo_p99 * 1e3, 2),
        "tenant_contended_p99_ttft_ms": round(cont_p99 * 1e3, 2),
        "tenant_flood_429s": flood_429,
        "tenant_flood_submitted": flood_n,
        "tenant_flood_admitted": flood_ok,
        "tenant_quiet_requests": t_n,
        "tenant_quiet_tenants": len(quiet),
        "tenant_lost_tokens": 0,
        "tenant_byte_exact": True,
    }


def remediation_leg(cfg, params) -> dict:
    """Closed-loop remediation (remediation/): two measurements.

    **Recovery latency** — a template-backend monitor server on a seeded
    FakeCluster runs the four chaos scenarios (crash loop, OOM, stale
    scheduler, node pressure) end to end: warning burst -> diagnosis ->
    constrained plan -> dry-run -> execute -> verification turn.  Reports
    inject->verified wall time per scenario.  Faults are injected purely
    as cluster-state mutations; every kube write goes through
    RemediationEngine (the raw-kube-write lint sweeps this file too).

    **Plan-decode overhead** — FSM-constrained plan decode vs free decode
    on the same engine geometry.  The per-step cost is one (state, token)
    mask gather; gate (hard): < 10% tok/s penalty.  Uses a dedicated
    vocab-300 tiny model (``cfg`` is ignored): the 259-token byte
    alphabet of the plan grammar does not fit the 256-entry tiny preset.
    """
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import ModelConfig
    from k8s_llm_monitor_tpu.monitor.cluster import (
        FakeCluster,
        seed_demo_cluster,
    )
    from k8s_llm_monitor_tpu.monitor.config import Config
    from k8s_llm_monitor_tpu.monitor.models import EventInfo
    from k8s_llm_monitor_tpu.monitor.server import build_server
    from k8s_llm_monitor_tpu.remediation import (
        TargetSnapshot,
        parse_plan,
        plan_fsm,
    )
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.utils.tokenizer import ByteTokenizer

    stats: dict = {}

    # -- part 1: inject -> verified-recovery latency, four scenarios --------
    mcfg = Config()
    mcfg.llm.provider = "template"
    mcfg.diagnosis.burst_threshold = 3
    mcfg.diagnosis.window_s = 60.0
    mcfg.diagnosis.cooldown_s = 0.0
    mcfg.remediation.execute = True
    mcfg.remediation.verify = True
    mcfg.remediation.verb_interval_s = 0.0
    mcfg.remediation.target_interval_s = 0.0
    backend = seed_demo_cluster(FakeCluster())
    backend.add_statefulset("engine-decode", replicas=2)
    srv = build_server(mcfg, backend=backend)
    srv.start()
    # Destructive verbs (delete_pod, cordon) refuse without an approval;
    # the bench measures the full closed loop, so grant the env approval
    # for its duration (and restore whatever the caller had).
    saved_approve = os.environ.get("K8SLLM_REMEDIATE_APPROVE")
    os.environ["K8SLLM_REMEDIATE_APPROVE"] = "1"

    def run_scenario(name, mutate, reason, message, want_verb, want_name):
        mutate()
        t0 = time.monotonic()
        for i in range(4):
            srv.diagnosis.handler.on_event(EventInfo(
                type="Warning", reason=reason,
                message=f"{message} (try {i})", source="bench"))
        deadline = t0 + 60.0
        while time.monotonic() < deadline:
            for rec in srv.remediation.records():
                if rec["plan"]["verb"] == want_verb \
                        and rec["plan"]["name"] == want_name \
                        and rec["status"] == "verified":
                    ms = (time.monotonic() - t0) * 1e3
                    stats[f"remediation_recovery_ms_{name}"] = round(ms, 2)
                    log(f"remediate: {name} -> {want_verb}/{want_name} "
                        f"verified in {ms:.1f} ms")
                    return
            time.sleep(0.01)
        raise AssertionError(
            f"remediate: {name} never verified; records "
            f"{[(r['plan']['verb'], r['status']) for r in srv.remediation.records()]}")

    try:
        run_scenario(
            "crash_loop",
            lambda: backend.update_pod("default", "web-frontend-7d4b9c6f5-x2x1p",
                                       phase="CrashLoopBackOff"),
            "BackOff",
            "Back-off restarting failed container in web-frontend",
            "rollout_restart", "web-frontend")
        run_scenario(
            "oom",
            lambda: backend.update_pod("default", "api-backend-6f5d8b7c9-k3k2m",
                                       phase="OOMKilled"),
            "OOMKilling", "Memory cgroup out of memory: api-backend",
            "rollout_restart", "api-backend")
        run_scenario(
            "stale_scheduler",
            lambda: backend.add_pod("batch-runner-5f7d8", phase="Pending",
                                    node=""),
            "FailedScheduling",
            "0/3 nodes available, unschedulable pod batch-runner-5f7d8 "
            "stuck Pending (stale scheduler cache)",
            "delete_pod", "batch-runner-5f7d8")
        run_scenario(
            "node_pressure",
            lambda: None,  # pressure arrives as events, not pod state
            "NodeHasMemoryPressure",
            "Node k3d-demo-agent-1 status is now: NodeHasMemoryPressure",
            "cordon", "k3d-demo-agent-1")
    finally:
        if saved_approve is None:
            os.environ.pop("K8SLLM_REMEDIATE_APPROVE", None)
        else:
            os.environ["K8SLLM_REMEDIATE_APPROVE"] = saved_approve
        srv.stop()
    stats["remediation_scenarios_verified"] = 4

    # -- part 2: constrained plan decode vs free decode ----------------------
    overhead_budget = float(os.environ.get("BENCH_REMEDIATE_BUDGET", "10.0"))
    reps = int(os.environ.get("BENCH_REMEDIATE_REPS", "4"))
    # Wide enough that the model step dominates: on a hidden-32 toy the
    # per-step mask gather alone reads as ~15% because the matmuls are
    # microscopic, which says nothing about serving-sized models.
    r_cfg = ModelConfig(name="tiny", vocab_size=300, hidden_size=128,
                        intermediate_size=256, num_layers=4, num_heads=4,
                        num_kv_heads=2, dtype="float32", rope_theta=1e4)
    tok = ByteTokenizer()
    r_params = llama.init_params(jax.random.PRNGKey(0), r_cfg)
    engine = InferenceEngine(
        r_cfg, r_params,
        EngineConfig(max_slots=4, num_blocks=512, block_size=16,
                     max_blocks_per_seq=128, prefill_buckets=(64,),
                     decode_steps_per_iter=4),
        tokenizer=tok)
    snap = TargetSnapshot.from_backend(backend, ["default"])
    engine.set_grammar(plan_fsm(snap, eos_id=tok.eos_id))
    prompts = [tok.encode("## Plan\nchoose one action:\n")] * 4

    def run_once(constrained, max_tokens):
        t0 = time.monotonic()
        results = engine.generate(
            prompts,
            SamplingParams(max_tokens=max_tokens, temperature=0.0,
                           constrained=constrained))
        dt = time.monotonic() - t0
        return sum(len(r.token_ids) for r in results) / dt, results

    # Warm both programs, and size the free run to the constrained plan
    # length so prefill amortization matches between the two modes.
    _, probe = run_once(True, 1)
    for res in probe:
        plan = parse_plan(tok.decode(res.token_ids), snap)
        assert plan["verb"], "constrained probe produced no plan"
    plan_len = max(8, round(sum(len(r.token_ids) for r in probe)
                            / len(probe)))
    run_once(False, plan_len)

    cons_tok_s = max(run_once(True, 1)[0] for _ in range(reps))
    free_tok_s = max(run_once(False, plan_len)[0] for _ in range(reps))
    overhead = max(0.0, (free_tok_s - cons_tok_s) / free_tok_s * 100.0)
    log(f"remediate: plan decode {cons_tok_s:.0f} tok/s constrained vs "
        f"{free_tok_s:.0f} free ({plan_len}-token plans) -> "
        f"{overhead:.2f}% overhead")
    assert overhead < overhead_budget, (
        f"plan-constrained decode costs {overhead:.2f}% tok/s "
        f"(budget {overhead_budget}%)")
    stats.update({
        "remediation_plan_overhead_pct": round(overhead, 2),
        "remediation_plan_tok_s_constrained": round(cons_tok_s, 1),
        "remediation_plan_tok_s_free": round(free_tok_s, 1),
        "remediation_plan_len_tokens": plan_len,
    })
    return stats


def kv_tier_leg(cfg, params) -> dict:
    """KV-tier rung 1 (serving/kv_tier.py): int8 resident KV must hold
    >= 1.8x the decode lanes of the model-dtype pool on the SAME pool
    bytes.  The byte math is exact (kv_cache.py:page_slice_bytes, scales
    included); the engine pair proves it end-to-end: two engines whose
    ``num_blocks`` are sized from one shared byte budget drain the same
    burst, and the peak concurrently-resident lane counts are compared.
    A greedy parity sample on identical prompts rides along (the
    tolerance-gated divergence budget lives in tests/test_kv_tier.py);
    a spill/restore pass exercises rung 2 and reports its counters."""
    import numpy as np

    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        GenerationRequest,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.serving.kv_cache import page_slice_bytes

    bs = 16
    model_itemsize = np.dtype(cfg.kv_dtype or cfg.dtype).itemsize
    page_model = page_slice_bytes(cfg.num_kv_heads, cfg.head_dim_, bs,
                                  model_itemsize, scale_bytes=0)
    page_int8 = page_slice_bytes(cfg.num_kv_heads, cfg.head_dim_, bs, 1,
                                 scale_bytes=4)
    byte_ratio = page_model / page_int8

    k_len, k_gen = 64, 40
    cap = k_len + k_gen + 1
    bps = (cap + bs - 1) // bs
    blocks_model = 4 * bps + 2              # 4 resident lanes + slack
    budget = blocks_model * page_model      # per (layer, k/v) slice
    blocks_int8 = budget // page_int8
    rng = np.random.default_rng(13)
    prompts = [[int(t) for t in rng.integers(4, cfg.vocab_size - 4,
                                             size=k_len)]
               for _ in range(16)]

    def run(kv_dtype: str, num_blocks: int):
        ecfg = EngineConfig(
            max_slots=16, num_blocks=int(num_blocks), block_size=bs,
            max_blocks_per_seq=bps, prefill_buckets=(k_len,),
            max_prefills_per_step=4, decode_steps_per_iter=4,
            prefix_cache_entries=0, kv_dtype=kv_dtype)
        eng = InferenceEngine(cfg, params, ecfg, eos_id=-1)
        eng.generate([prompts[0]], SamplingParams(max_tokens=4))  # warm
        for i, p in enumerate(prompts):
            eng.submit(GenerationRequest(
                request_id=f"kv-{i}", prompt_ids=p,
                sampling=SamplingParams(max_tokens=k_gen)))
        peak = 0
        while eng.has_work:
            eng.step()
            peak = max(peak, eng.active_slots)
        res = [eng.poll(f"kv-{i}") for i in range(len(prompts))]
        assert all(r is not None and r.finish_reason != "error"
                   for r in res)
        streams = [r.token_ids for r in res]
        del eng
        return peak, streams

    lanes_model, ref_streams = run("auto", blocks_model)
    lanes_int8, q_streams = run("int8", blocks_int8)
    lanes_ratio = lanes_int8 / max(lanes_model, 1)
    # Greedy agreement prefix across the identical-prompt streams: int8
    # dequant error can flip near-tied argmaxes, so this is a sample, not
    # a gate (the gated budget is test_kv_tier.py's parity test).
    agree = []
    for a, b in zip(ref_streams, q_streams):
        m = 0
        while m < min(len(a), len(b)) and a[m] == b[m]:
            m += 1
        agree.append(m / max(len(a), 1))
    parity = float(np.median(agree))
    log(f"kv tier: int8 page {page_int8} B vs {cfg.kv_dtype or cfg.dtype} "
        f"{page_model} B ({byte_ratio:.2f}x byte ratio); peak resident "
        f"lanes {lanes_int8} vs {lanes_model} ({lanes_ratio:.2f}x) on "
        f"{budget * 2 * cfg.num_layers / 2**20:.1f} MiB pool; greedy "
        f"parity prefix {parity:.2f}")

    # Rung 2 spill/restore: a pool that holds ~2 cached prefixes cycles
    # through 4, so pressured evictions spill to the host tier and the
    # second pass restores instead of re-prefilling.
    spills = restores = -1
    try:
        # Pool sized well under 6 resident prefixes: cycling 6 distinct
        # prefixes forces pressured evictions (spills); the second pass
        # rehydrates the spilled ones instead of re-prefilling.
        s_ecfg = EngineConfig(
            max_slots=4, num_blocks=2 * bps + 2, block_size=bs,
            max_blocks_per_seq=bps, prefill_buckets=(k_len,),
            max_prefills_per_step=2, decode_steps_per_iter=4,
            kv_dtype="int8", host_spill_bytes=256 << 20)
        seng = InferenceEngine(cfg, params, s_ecfg, eos_id=-1)
        for _round in range(2):
            for p in prompts[:6]:
                seng.generate([p], SamplingParams(max_tokens=4))
        st = seng.kv_tier_stats()
        spills, restores = st["spills"], st["restores"]
        log(f"kv tier spill/restore: {spills} spills, {restores} restores,"
            f" host {st['host_bytes'] / 2**20:.1f} MiB "
            f"({st['host_entries']} entries)")
        del seng
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"kv tier spill pass skipped: {exc}")
    return {
        "kv_tier_page_bytes_model": page_model,
        "kv_tier_page_bytes_int8": page_int8,
        "kv_tier_byte_ratio": round(byte_ratio, 3),
        "kv_tier_resident_lanes_model": lanes_model,
        "kv_tier_resident_lanes_int8": lanes_int8,
        "kv_tier_lanes_ratio": round(lanes_ratio, 3),
        "kv_tier_parity_prefix": round(parity, 3),
        "kv_spills": spills,
        "kv_restores": restores,
    }


def migration_leg(cfg, params) -> dict:
    """KV-tier rung 3 (fleet/router.py): on a prefix-affinity miss the
    router moves the owning replica's shared KV pages to the target
    instead of re-prefilling.  This leg measures the miss TTFT both ways
    on identical prompts — cold re-prefill on one replica vs
    fetch+install+decode on another — with every compiled shape warmed
    first, so the ratio is pure scheduling + page movement."""
    import numpy as np

    from k8s_llm_monitor_tpu.fleet import LocalReplica
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.serving.service import EngineService

    m_len = int(os.environ.get("BENCH_MIG_PROMPT_LEN", "769"))
    cap = m_len + 24
    ecfg = EngineConfig(
        max_slots=4, num_blocks=4 * ((cap + 15) // 16) + 16, block_size=16,
        max_blocks_per_seq=(cap + 15) // 16, prefill_buckets=(64,),
        max_prefills_per_step=2, decode_steps_per_iter=4)

    def rep(name: str) -> LocalReplica:
        return LocalReplica(name, service=EngineService(
            InferenceEngine(cfg, params, ecfg, eos_id=-1)))

    rng = np.random.default_rng(17)

    def mk_prompt() -> list[int]:
        return [int(t) for t in
                rng.integers(4, cfg.vocab_size - 4, size=m_len)]

    warm, warm2, p = mk_prompt(), mk_prompt(), mk_prompt()
    owner, cold, target = rep("mig-owner"), rep("mig-cold"), rep("mig-tgt")
    try:
        sp = SamplingParams(max_tokens=4)
        for r in (owner, cold, target):
            # Two passes: the first compiles the chunk-round programs, the
            # second (a prefix hit) compiles the suffix-sized hit path.
            r.generate(warm, sp).result(timeout=600.0)
            r.generate(warm, sp).result(timeout=600.0)
        # Warm the move path itself: export on the owner and install on the
        # target each compile a one-time gather/scatter program (~100+ ms)
        # that must not be billed to the measured migration.  The warmup
        # blob is a prefix the target has NOT seen — installing an
        # already-cached prefix short-circuits before the scatter.
        owner.generate(warm2, sp).result(timeout=600.0)
        wblob = owner.fetch_prefix(warm2, tenant=TEN)
        assert wblob is not None and target.install_prefix(
            wblob, tenant=TEN) == "installed"
        owner.generate(p, sp).result(timeout=600.0)   # owner caches p
        reprefill_s = cold.generate(p, sp).result(timeout=600.0).ttft_s
        t0 = time.monotonic()
        blob = owner.fetch_prefix(p, tenant=TEN)
        assert blob is not None, "owner lost the prefix"
        outcome = target.install_prefix(blob, tenant=TEN)
        assert outcome == "installed", outcome
        move_s = time.monotonic() - t0
        migration_s = move_s + target.generate(p, sp).result(
            timeout=600.0).ttft_s
    finally:
        for r in (owner, cold, target):
            r.close()
    ratio = migration_s / max(reprefill_s, 1e-9)
    log(f"prefix migration ({m_len}-token prompt, {len(blob)} B blob): "
        f"miss TTFT {migration_s * 1e3:.1f} ms migrated "
        f"(fetch+install {move_s * 1e3:.1f} ms) vs {reprefill_s * 1e3:.1f} "
        f"ms re-prefilled ({ratio:.2f}x; budget <= 0.5x)")
    return {
        "migration_ttft_ms": round(migration_s * 1e3, 2),
        "migration_reprefill_ttft_ms": round(reprefill_s * 1e3, 2),
        "migration_ttft_ratio": round(ratio, 3),
        "migration_blob_bytes": len(blob),
        "migration_prompt_len": m_len,
    }


def tracing_leg(cfg, params) -> dict:
    """Tracing overhead (observability/tracing.py): the identical burst
    through one engine with span recording fully sampled vs fully off.
    The delta is the acceptance number — default sampling must cost <2%
    tok/s.  A throwaway warm-up run absorbs per-engine jit/compile cost
    so both measured runs see the same caches."""
    import numpy as np

    from k8s_llm_monitor_tpu.observability.tracing import (
        Tracer,
        get_tracer,
        set_tracer,
    )
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.serving.service import EngineService

    rng = np.random.default_rng(11)
    t_len = int(os.environ.get("BENCH_TRACE_PROMPT_LEN", "64"))
    t_gen = int(os.environ.get("BENCH_TRACE_MAX_TOKENS", "32"))
    t_n = int(os.environ.get("BENCH_TRACE_CONCURRENCY", "16"))
    t_cap = t_len + t_gen + 16
    t_ecfg = EngineConfig(
        max_slots=8,
        num_blocks=8 * ((t_cap + 15) // 16) + 16,
        block_size=16,
        max_blocks_per_seq=(t_cap + 15) // 16,
        prefill_buckets=(t_len,),
        max_prefills_per_step=8,
        decode_steps_per_iter=4,
    )
    prompts = [[int(t) for t in
                rng.integers(4, cfg.vocab_size - 4, size=t_len)]
               for _ in range(t_n)]

    def run_once(sample: float) -> tuple[float, int]:
        tracer = Tracer(sample=sample, seed=11)
        set_tracer(tracer)
        svc = EngineService(InferenceEngine(cfg, params, t_ecfg, eos_id=-1))
        try:
            t0 = time.monotonic()
            handles = [svc.submit(p, SamplingParams(max_tokens=t_gen))
                       for p in prompts]
            for h in handles:
                res = h.result(timeout=600.0)
                assert res.finish_reason == "length", res.error
            wall = time.monotonic() - t0
        finally:
            svc.stop(timeout=10.0)
        return t_n * t_gen / wall, tracer.recorded

    # Interleaved best-of-N pairs: per-span cost is microseconds, so on a
    # small config a single pair is dominated by scheduler/alloc noise.
    # Best-of filters that noise from both sides of the comparison.
    reps = int(os.environ.get("BENCH_TRACE_REPS", "3"))
    prev = get_tracer()
    off_tok_s, on_tok_s, spans = 0.0, 0.0, 0
    try:
        run_once(1.0)  # warm-up, discarded
        for _ in range(reps):
            off, _ = run_once(0.0)
            on, n_spans = run_once(1.0)
            off_tok_s = max(off_tok_s, off)
            if on > on_tok_s:
                on_tok_s, spans = on, n_spans
    finally:
        set_tracer(prev)
    overhead_pct = (100.0 * (off_tok_s - on_tok_s) / off_tok_s
                    if off_tok_s > 0 else 0.0)
    log(f"tracing: sampled {on_tok_s:.1f} tok/s vs off {off_tok_s:.1f} "
        f"tok/s ({overhead_pct:+.2f}% overhead, {spans} spans; "
        f"budget < 2%)")
    return {
        "tracing_off_tok_s": round(off_tok_s, 1),
        "tracing_sampled_tok_s": round(on_tok_s, 1),
        "tracing_overhead_pct": round(overhead_pct, 2),
        "tracing_spans_recorded": spans,
        "tracing_overhead_budget_pct": 2.0,
    }


def signals_leg(cfg, params) -> dict:
    """Telemetry-plane overhead (observability/signals.py): the identical
    burst through one engine with the signal scraper sampling at 40x the
    default cadence vs no scraper at all.  The delta is the acceptance
    number — the scraper must cost < 1% tok/s (it reads a handful of
    counters per pass; anything visible means it grew a hot path).  The
    final derived-signal snapshot rides along in the extras, so the bench
    JSON doubles as a fleet-signal fixture."""
    import types

    import numpy as np

    from k8s_llm_monitor_tpu.monitor.config import TelemetryConfig
    from k8s_llm_monitor_tpu.observability.signals import SignalScraper
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.serving.service import EngineService

    rng = np.random.default_rng(19)
    s_len = int(os.environ.get("BENCH_SIGNALS_PROMPT_LEN", "64"))
    s_gen = int(os.environ.get("BENCH_SIGNALS_MAX_TOKENS", "32"))
    s_n = int(os.environ.get("BENCH_SIGNALS_CONCURRENCY", "16"))
    s_cap = s_len + s_gen + 16
    s_ecfg = EngineConfig(
        max_slots=8,
        num_blocks=8 * ((s_cap + 15) // 16) + 16,
        block_size=16,
        max_blocks_per_seq=(s_cap + 15) // 16,
        prefill_buckets=(s_len,),
        max_prefills_per_step=8,
        decode_steps_per_iter=4,
    )
    prompts = [[int(t) for t in
                rng.integers(4, cfg.vocab_size - 4, size=s_len)]
               for _ in range(s_n)]
    last_signals: dict = {}

    def run_once(scrape: bool) -> float:
        nonlocal last_signals
        svc = EngineService(InferenceEngine(cfg, params, s_ecfg, eos_id=-1))
        scraper = None
        if scrape:
            scraper = SignalScraper(cfg=TelemetryConfig(
                scrape_interval_s=0.05))
            scraper.attach(types.SimpleNamespace(
                engine_service=lambda: svc, fleet_router=lambda: None))
            scraper.start()
        try:
            t0 = time.monotonic()
            handles = [svc.submit(p, SamplingParams(max_tokens=s_gen))
                       for p in prompts]
            for h in handles:
                res = h.result(timeout=600.0)
                assert res.finish_reason == "length", res.error
            wall = time.monotonic() - t0
        finally:
            if scraper is not None:
                scraper.scrape_once()  # final post-drain sample
                last_signals = scraper.signals()
                scraper.stop()
            svc.stop(timeout=10.0)
        return s_n * s_gen / wall

    # Interleaved best-of-N, same rationale as the tracing leg: the
    # scraper's per-pass cost is microseconds of counter reads, so a
    # single pair is pure scheduler noise at this engine size.
    reps = int(os.environ.get("BENCH_SIGNALS_REPS", "3"))
    off_tok_s = on_tok_s = 0.0
    run_once(False)  # warm-up, discarded
    for _ in range(reps):
        off_tok_s = max(off_tok_s, run_once(False))
        on_tok_s = max(on_tok_s, run_once(True))
    overhead_pct = (100.0 * (off_tok_s - on_tok_s) / off_tok_s
                    if off_tok_s > 0 else 0.0)
    scraper_stats = last_signals.get("scraper") or {}
    local = (last_signals.get("targets") or {}).get("local") or {}
    log(f"signals: scraped {on_tok_s:.1f} tok/s vs off {off_tok_s:.1f} "
        f"tok/s ({overhead_pct:+.2f}% overhead, "
        f"{scraper_stats.get('scrapes', 0)} scrapes, "
        f"{scraper_stats.get('series', 0)} series; budget < 1%)")
    assert overhead_pct < 1.0, (
        f"signal scraper overhead {overhead_pct:.2f}% exceeds the 1% "
        f"budget ({on_tok_s:.1f} vs {off_tok_s:.1f} tok/s)")
    return {
        "signals_off_tok_s": round(off_tok_s, 1),
        "signals_on_tok_s": round(on_tok_s, 1),
        "signals_overhead_pct": round(overhead_pct, 2),
        "signals_overhead_budget_pct": 1.0,
        "signals_scrapes": scraper_stats.get("scrapes", 0),
        "signals_series": scraper_stats.get("series", 0),
        # The local target's derived block from the drained burst — the
        # autoscaler-contract shape, persisted with the bench artifact.
        "signals_snapshot": {
            "scale_hint": local.get("scale_hint"),
            "queue_tokens_total": local.get("queue_tokens_total"),
            "queue_growth_total_tok_per_s":
                local.get("queue_growth_total_tok_per_s"),
            "brownout_dwell": local.get("brownout_dwell"),
            "headroom_tokens": local.get("headroom_tokens"),
            "anomalies": local.get("anomalies"),
        },
    }


def elasticity_leg(cfg, params) -> dict:
    """Disaggregated-fleet elasticity smoke (fleet/autoscaler.py +
    docs/fleet.md "Disaggregated roles & autoscaling").  Three numbers:

    - reaction: wall time from a scale_hint flipping "up" to the
      controller invoking the executor (sense→decide through the gate
      ladder), with the warm-spawn time reported separately — replica
      cold-start dominates real reaction and deserves its own line.
    - churn-vs-steady TTFT p99: the identical burst with the controller
      idle vs with a scale-up AND a drain-based scale-down landing
      mid-burst.  Elasticity must not wreck the interactive tail.
    - handoff-vs-local-prefill TTFT: first-token latency continuing a
      prompt whose KV prefix was exported/installed (suffix-only prefill)
      vs re-prefilling the same prompt cold.  The point of shipping KV is
      that this ratio stays <= 0.5x — asserted.
    """
    import threading

    import numpy as np

    from k8s_llm_monitor_tpu.fleet import (
        AutoscaleController,
        FleetRouter,
        LocalPoolExecutor,
        LocalReplica,
        ReplicaRegistry,
    )
    from k8s_llm_monitor_tpu.monitor.config import AutoscaleConfig
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.serving.service import EngineService

    e_len = int(os.environ.get("BENCH_ELASTIC_PROMPT_LEN", "64"))
    e_gen = int(os.environ.get("BENCH_ELASTIC_MAX_TOKENS", "8"))
    e_n = int(os.environ.get("BENCH_ELASTIC_CONCURRENCY", "12"))
    seq_blocks = (e_len + e_gen) // 8 + 4
    ecfg = EngineConfig(
        max_slots=4,
        num_blocks=8 * seq_blocks + 16,
        block_size=8,
        max_blocks_per_seq=seq_blocks,
        prefill_buckets=(16, e_len),
        max_prefills_per_step=4,
        decode_steps_per_iter=4,
    )
    rng = np.random.default_rng(31)

    def rand_prompt(n):
        return [int(t) for t in rng.integers(4, cfg.vocab_size - 4, size=n)]

    def warm(rep):
        # Compile the full-prefill AND the suffix-only prefill path (what
        # a handoff continuation runs) before any measured dispatch.
        w = rand_prompt(e_len)
        first = rep.generate(w, SamplingParams(max_tokens=2)).result(
            timeout=600.0)
        rep.generate(w + first.token_ids[:1],
                     SamplingParams(max_tokens=2)).result(timeout=600.0)

    def new_replica(role, rid):
        eng = InferenceEngine(cfg, params, ecfg, eos_id=-1)
        rep = LocalReplica(rid, service=EngineService(eng), role=role)
        warm(rep)
        return rep

    reg = ReplicaRegistry()
    reps = [new_replica("prefill", "prefill-0"),
            new_replica("decode", "decode-0")]
    for r in reps:
        reg.add(r)
    reg.refresh()
    router = FleetRouter(reg, policy="affinity", affinity_prefix_tokens=16)

    closers = list(reps)

    def burst(mid=None):
        recs = []
        for _ in range(e_n):
            p = rand_prompt(e_len)
            t0 = time.monotonic()
            recs.append((t0, router.submit(p,
                                           SamplingParams(max_tokens=e_gen))))
        if mid is not None:
            mid()
        lat: list[float] = []

        def consume(t0, h):
            it = h.stream(timeout=600.0)
            next(it)
            dt = time.monotonic() - t0
            for _ in it:
                pass
            res = h.result(timeout=600.0)
            assert res.finish_reason == "length", res.error
            lat.append(dt)

        threads = [threading.Thread(target=consume, args=rec, daemon=True)
                   for rec in recs]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600.0)
        assert len(lat) == e_n
        lat.sort()
        return lat

    steady = burst()

    # -- elasticity mid-burst: scale-up, then drain-based scale-down ------
    sig_targets: dict = {}

    class _Sig:
        def signals(self):
            return {"targets": dict(sig_targets)}

    decided: dict = {}

    class _TimedPool(LocalPoolExecutor):
        def scale(self, role, replicas, dry_run=False):
            if not dry_run and "t" not in decided:
                decided["t"] = time.monotonic()
            return super().scale(role, replicas, dry_run)

    def tracked_factory(role, rid):
        rep = new_replica(role, rid)
        closers.append(rep)
        return rep

    executor = _TimedPool(reg, tracked_factory)
    for r in reps:
        executor.adopt(r.role, r)
    ctl = AutoscaleController(
        _Sig(), executor,
        AutoscaleConfig(enabled=True, cooldown_s=0.05,
                        scale_down_dwell_s=0.2, min_prefill=1, max_prefill=2,
                        min_decode=1, max_decode=3, flap_max_flips=50),
        registry=reg)
    reaction: dict = {}

    def churn():
        t0 = time.monotonic()
        sig_targets["decode-0"] = {"scale_hint": "up",
                                   "anomalies": ["queue_growth"],
                                   "stale": False}
        deadline = t0 + 120.0
        while (("decode", "up", "applied") not in ctl.actions_total
               and time.monotonic() < deadline):
            ctl.tick()
            time.sleep(0.01)
        assert ("decode", "up", "applied") in ctl.actions_total
        reaction["decide_s"] = decided["t"] - t0
        reaction["spawn_s"] = time.monotonic() - t0
        sig_targets["decode-0"] = {"scale_hint": "down", "anomalies": [],
                                   "stale": False}
        deadline = time.monotonic() + 120.0
        while (("decode", "down", "applied") not in ctl.actions_total
               and time.monotonic() < deadline):
            ctl.tick()
            time.sleep(0.02)

    churn_lat = burst(mid=churn)
    executor.reap()

    def pct(sorted_lat, q):
        return sorted_lat[min(len(sorted_lat) - 1,
                              int(len(sorted_lat) * q))]

    steady_p99 = pct(steady, 0.99)
    churn_p99 = pct(churn_lat, 0.99)
    churn_ratio = churn_p99 / steady_p99 if steady_p99 > 0 else 0.0

    # -- handoff vs cold-prefill TTFT (replica level, best-of-3) ----------
    # Long prompt on purpose: the ratio is only meaningful once prefill
    # compute dominates the fixed per-dispatch engine-loop cost (~10 ms
    # on CPU) — at diagnosis-prompt sizes the gap is far larger still.
    h_len = int(os.environ.get("BENCH_ELASTIC_HANDOFF_PROMPT_LEN", "1024"))
    # The prefix cache publishes whole blocks only and always keeps the
    # final prompt token unshared (kv_cache.shareable_blocks), so a
    # block-aligned owner prompt caches one block short and leaves the
    # continuation a (block_size + 1)-token suffix — just past the small
    # prefill bucket, i.e. full-prefill cost.  Snap to one token below
    # alignment: the continuation then carries exactly one bucket-16
    # suffix beyond the shipped prefix.
    h_len = max(256, h_len // 16 * 16) - 1
    h_blocks = h_len // 16 + 4
    hcfg = EngineConfig(
        max_slots=2,
        num_blocks=5 * h_blocks + 16,  # 4 pinned prefixes + an active seq
        block_size=16,
        max_blocks_per_seq=h_blocks,
        prefill_buckets=(16, h_len + 64),
        max_prefills_per_step=2,
        decode_steps_per_iter=4,
    )

    def h_rep(rid):
        eng = InferenceEngine(cfg, params, hcfg, eos_id=-1)
        rep = LocalReplica(rid, service=EngineService(eng), role="unified")
        w = rand_prompt(h_len)
        first = rep.generate(w, SamplingParams(max_tokens=2)).result(
            timeout=600.0)
        rep.generate(w + first.token_ids[:1],
                     SamplingParams(max_tokens=2)).result(timeout=600.0)
        closers.append(rep)
        return rep

    owner, target, cold = h_rep("h-owner"), h_rep("h-target"), h_rep("h-cold")

    def ttft_once(rep, prompt):
        t0 = time.monotonic()
        h = rep.generate(prompt, SamplingParams(max_tokens=2))
        it = h.stream(timeout=600.0)
        next(it)
        dt = time.monotonic() - t0
        for _ in it:
            pass
        h.result(timeout=600.0)
        return dt

    handoff_ts, cold_ts = [], []
    for _ in range(3):
        p = rand_prompt(h_len)
        first = owner.generate(p, SamplingParams(max_tokens=1)).result(
            timeout=600.0)
        cont = p + first.token_ids[:1]
        blob = owner.fetch_prefix(cont, tenant=TEN)
        assert blob is not None, "owner exported no prefix"
        outcome = target.install_prefix(blob, tenant=TEN)
        assert outcome in ("installed", "cached"), outcome
        handoff_ts.append(ttft_once(target, cont))
        cold_ts.append(ttft_once(cold, cont))
    handoff_ttft, cold_ttft = min(handoff_ts), min(cold_ts)
    handoff_ratio = handoff_ttft / cold_ttft if cold_ttft > 0 else 0.0

    for rep in closers:
        rep.close()

    actions = {"/".join(k): v for k, v in sorted(ctl.actions_total.items())}
    log(f"elastic: decide {reaction['decide_s'] * 1e3:.1f} ms, warm spawn "
        f"{reaction['spawn_s']:.2f} s; TTFT p99 churn {churn_p99 * 1e3:.1f} "
        f"ms vs steady {steady_p99 * 1e3:.1f} ms ({churn_ratio:.2f}x); "
        f"handoff TTFT {handoff_ttft * 1e3:.1f} ms vs cold prefill "
        f"{cold_ttft * 1e3:.1f} ms ({handoff_ratio:.2f}x, budget <= 0.5x)")
    assert handoff_ratio <= 0.5, (
        f"handoff continuation TTFT {handoff_ttft * 1e3:.1f} ms is "
        f"{handoff_ratio:.2f}x a cold prefill ({cold_ttft * 1e3:.1f} ms); "
        "shipping the KV prefix should at least halve it")
    return {
        "elastic_reaction_decide_ms": round(reaction["decide_s"] * 1e3, 2),
        "elastic_reaction_spawn_s": round(reaction["spawn_s"], 2),
        "elastic_steady_ttft_p50_ms": round(pct(steady, 0.5) * 1e3, 1),
        "elastic_steady_ttft_p99_ms": round(steady_p99 * 1e3, 1),
        "elastic_churn_ttft_p99_ms": round(churn_p99 * 1e3, 1),
        "elastic_churn_vs_steady_p99": round(churn_ratio, 2),
        "elastic_handoff_ttft_ms": round(handoff_ttft * 1e3, 2),
        "elastic_cold_prefill_ttft_ms": round(cold_ttft * 1e3, 2),
        "elastic_handoff_vs_local_ttft": round(handoff_ratio, 3),
        "elastic_handoff_budget": 0.5,
        "elastic_autoscale_actions": actions,
    }


def mesh_leg(cfg, params) -> dict:
    """ICI-sharded serving leg: ONE tensor-parallel engine over every local
    device (weights column/row-sharded, KV pages head-sharded — parallel/
    sharding.py), measured p50/p99 TTFT and throughput.  This is the
    multi-chip number: it replaces the old per-chip-equivalence arithmetic
    (burst/8 through one chip), which modeled neither the collectives nor
    the shared-KV-pool batching dynamics of a real slice.  Off-TPU the same
    leg runs on the forced-host-device mesh and is annotated as a dryrun —
    program structure and parity are exercised; the timings are not ICI.
    """
    import numpy as np
    import jax

    from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        GenerationRequest,
        InferenceEngine,
        SamplingParams,
    )

    devs = jax.devices()
    if len(devs) < 2:
        raise RuntimeError(
            "mesh leg needs >= 2 devices (set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 for a CPU dryrun)")
    mesh = create_mesh(MeshConfig(model=len(devs)))
    dryrun = devs[0].platform != "tpu"

    m_len = int(os.environ.get("BENCH_MESH_PROMPT_LEN",
                               os.environ.get("BENCH_PROMPT_LEN", "192")))
    m_gen = int(os.environ.get("BENCH_MESH_MAX_TOKENS",
                               os.environ.get("BENCH_MAX_TOKENS", "48")))
    m_n = int(os.environ.get("BENCH_MESH_CONCURRENCY",
                             os.environ.get("BENCH_CONCURRENCY", "100")))
    m_slots = int(os.environ.get("BENCH_MESH_SLOTS", "32"))
    cap = m_len + m_gen + 1
    bucket = bucket64(m_len)
    ecfg = EngineConfig(
        max_slots=m_slots,
        num_blocks=m_slots * ((cap + 15) // 16) + 16,
        block_size=16,
        max_blocks_per_seq=(cap + 15) // 16,
        prefill_buckets=(bucket,),
        max_prefills_per_step=min(16, m_slots),
        max_admission_rounds=8,
        decode_steps_per_iter=int(os.environ.get("BENCH_DECODE_STEPS", "8")),
    )
    eng = InferenceEngine(cfg, params, ecfg, eos_id=-1, mesh=mesh)
    rng = np.random.default_rng(3)

    def m_prompt() -> list[int]:
        return [int(t) for t in
                rng.integers(4, cfg.vocab_size - 4, size=m_len)]

    # Warm the admission-lane ladder so measured TTFT excludes compiles.
    log(f"mesh leg: {len(devs)}x {devs[0].device_kind} "
        f"({'DRYRUN: host devices, not ICI' if dryrun else 'measured'}); "
        f"warming compiled shapes...")
    w = ecfg.max_prefills_per_step
    while w >= 1:
        eng.generate([m_prompt() for _ in range(w)],
                     SamplingParams(max_tokens=4))
        w //= 2

    t0 = time.monotonic()
    for i in range(m_n):
        eng.submit(GenerationRequest(
            request_id=f"mesh-{i}", prompt_ids=m_prompt(),
            sampling=SamplingParams(max_tokens=m_gen)))
    while eng.has_work:
        eng.step()
    wall = time.monotonic() - t0
    res = [eng.poll(f"mesh-{i}") for i in range(m_n)]
    assert all(r is not None and r.finish_reason != "error" for r in res)
    t = np.array(sorted(r.ttft_s for r in res))
    p50_ms = float(np.percentile(t, 50)) * 1e3
    p99_ms = float(np.percentile(t, 99)) * 1e3
    tok_s = sum(len(r.token_ids) for r in res) / wall

    coll_share = 0.0
    try:
        eng.profile_decode_phases()
        coll_share = eng.decode_collective_share
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"mesh collective-share probe skipped: {exc}")

    log(f"mesh ({len(devs)} devices, {m_n} concurrent): "
        f"p50 TTFT {p50_ms:.1f} ms, p99 {p99_ms:.1f} ms, "
        f"{tok_s:.1f} tok/s, est collective share {coll_share:.0%}")
    return {
        "mesh_p50_ttft_ms": round(p50_ms, 2),
        "mesh_p99_ttft_ms": round(p99_ms, 2),
        "mesh_tok_s": round(tok_s, 1),
        "mesh_devices": len(devs),
        "mesh_device_kind": devs[0].device_kind,
        "mesh_concurrency": m_n,
        "mesh_dryrun": dryrun,
        "mesh_collective_share_est": round(coll_share, 4),
    }


def overlap_leg(cfg, params) -> dict:
    """Latency-hiding TP decode (parallel/overlap.py): overlap-on vs
    overlap-off engines on the same mesh, per-step decode time for each,
    and the resulting ``decode_collective_hidden_share`` — measured
    against the ring byte model on TPU, the analytic weight-streaming
    window in the CPU dryrun (engine.estimate_hidden_share).  A small
    TTFT burst runs through the overlap-on engine so the mesh JSON also
    carries end-to-end percentiles for the schedule that actually serves.

    When the bench model cannot take the staged schedule on this device
    count (e.g. the "tiny" preset's 2 KV heads under TP-8 — pages would
    replicate), the leg substitutes a TP-aligned tiny stand-in and labels
    it, so the dryrun still gates the schedule end to end.
    """
    import numpy as np
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.parallel.mesh import MeshConfig, create_mesh
    from k8s_llm_monitor_tpu.parallel.overlap import overlap_supported
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        GenerationRequest,
        InferenceEngine,
        SamplingParams,
    )

    devs = jax.devices()
    if len(devs) < 2:
        raise RuntimeError("overlap leg needs >= 2 devices")
    mesh = create_mesh(MeshConfig(model=len(devs)))
    dryrun = devs[0].platform != "tpu"

    why_not = overlap_supported(cfg, mesh)
    model_name = cfg.name
    if why_not:
        import dataclasses

        log(f"overlap leg: {cfg.name} unsupported ({why_not}); "
            f"measuring a TP-aligned tiny stand-in")
        cfg = dataclasses.replace(cfg, name="tiny-tp", num_heads=8,
                                  num_kv_heads=8, num_experts=0,
                                  sandwich_norms=False, qkv_bias=False)
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        model_name = cfg.name

    o_len = int(os.environ.get("BENCH_MESH_PROMPT_LEN", "48"))
    o_gen = int(os.environ.get("BENCH_MESH_MAX_TOKENS", "12"))
    o_n = int(os.environ.get("BENCH_MESH_CONCURRENCY", "12"))
    o_slots = int(os.environ.get("BENCH_MESH_SLOTS", "8"))
    cap = o_len + o_gen + 1
    ecfg_kw = dict(
        max_slots=o_slots,
        num_blocks=o_slots * ((cap + 15) // 16) + 16,
        block_size=16,
        max_blocks_per_seq=(cap + 15) // 16,
        prefill_buckets=(bucket64(o_len),),
        max_prefills_per_step=min(16, o_slots),
        max_admission_rounds=8,
        decode_steps_per_iter=int(os.environ.get("BENCH_DECODE_STEPS", "8")),
    )
    rng = np.random.default_rng(7)

    def o_prompt() -> list[int]:
        return [int(t) for t in
                rng.integers(4, cfg.vocab_size - 4, size=o_len)]

    def build(tp_overlap: str) -> InferenceEngine:
        eng = InferenceEngine(cfg, params,
                              EngineConfig(tp_overlap=tp_overlap, **ecfg_kw),
                              eos_id=-1, mesh=mesh)
        eng.generate([o_prompt() for _ in range(2)],
                     SamplingParams(max_tokens=4))  # warm compiles
        return eng

    eng_off = build("off")
    t_off = eng_off.profile_decode_phases()["decode_step_ms_short_ctx"]
    del eng_off
    eng_on = build("on")
    assert eng_on.tp_overlap
    t_on = eng_on.profile_decode_phases()["decode_step_ms_short_ctx"]
    hidden = eng_on.estimate_hidden_share(step_ms_on=t_on,
                                          step_ms_off=t_off)

    t0 = time.monotonic()
    for i in range(o_n):
        eng_on.submit(GenerationRequest(
            request_id=f"ov-{i}", prompt_ids=o_prompt(),
            sampling=SamplingParams(max_tokens=o_gen)))
    while eng_on.has_work:
        eng_on.step()
    wall = time.monotonic() - t0
    res = [eng_on.poll(f"ov-{i}") for i in range(o_n)]
    assert all(r is not None and r.finish_reason != "error" for r in res)
    t = np.array(sorted(r.ttft_s for r in res))
    p50_ms = float(np.percentile(t, 50)) * 1e3
    p99_ms = float(np.percentile(t, 99)) * 1e3
    tok_s = sum(len(r.token_ids) for r in res) / wall

    log(f"overlap ({model_name}, {len(devs)} devices): decode step "
        f"{t_on:.2f} ms on vs {t_off:.2f} ms off, hidden share "
        f"{hidden:.0%}{' (analytic dryrun)' if dryrun else ''}; "
        f"p50 TTFT {p50_ms:.1f} ms, p99 {p99_ms:.1f} ms, {tok_s:.1f} tok/s")
    return {
        "overlap_model": model_name,
        "overlap_decode_step_ms_on": round(t_on, 3),
        "overlap_decode_step_ms_off": round(t_off, 3),
        "decode_collective_hidden_share": round(hidden, 4),
        "overlap_hidden_share_analytic": dryrun,
        "overlap_p50_ttft_ms": round(p50_ms, 2),
        "overlap_p99_ttft_ms": round(p99_ms, 2),
        "overlap_tok_s": round(tok_s, 1),
    }


def tier_admission_leg(cfg, params) -> dict:
    """Tier-aware admission (engine.admission_headroom_tokens): at EQUAL
    device pool bytes, an engine whose device blocks are pinned by
    spillable prefix-cache content admits a burst under
    ``kv_admission="tier"`` (the host tier can take the spill losslessly)
    that ``kv_admission="device"`` sheds.  Every admitted lane must
    finish clean with its full token budget while ``lane_eviction``
    faults are armed — the zero-lost-tokens clause.
    """
    import numpy as np

    from k8s_llm_monitor_tpu.resilience.faults import get_injector
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        GenerationRequest,
        InferenceEngine,
        SamplingParams,
    )

    bs = 16
    seed_len = 64       # 4 full blocks each stay pinned in the prefix cache
    a_len, a_gen = 120, 8
    bps = (a_len + a_gen + 1 + bs - 1) // bs
    n_burst = 6
    rng = np.random.default_rng(23)

    def a_prompt(n: int) -> list[int]:
        return [int(t) for t in rng.integers(4, cfg.vocab_size - 4, size=n)]

    # Pool sized so the seeds' cacheable blocks pin most of it: each seed
    # publishes shareable_blocks(64,16)=3 blocks, 12 pinned of 17 usable.
    # Device-only headroom after seeding is 5 blocks = 80 tokens < the
    # 121 a burst lane needs; the tier policy counts the 12 evictable
    # (host-spillable) blocks too and admits.
    seed_prompts = [a_prompt(seed_len) for _ in range(4)]
    num_blocks = 18

    def run(kv_admission: str):
        ecfg = EngineConfig(
            max_slots=4, num_blocks=num_blocks, block_size=bs,
            max_blocks_per_seq=bps, prefill_buckets=(64, 128),
            max_prefills_per_step=2, decode_steps_per_iter=4,
            prefix_cache_entries=64, host_spill_bytes=64 << 20,
            kv_admission=kv_admission)
        eng = InferenceEngine(cfg, params, ecfg, eos_id=-1)
        # Fill the device pool with published (evictable) prefixes.
        for p in seed_prompts:
            eng.generate([p], SamplingParams(max_tokens=1))
        admitted, shed = [], 0
        get_injector().reset(seed=1234)
        get_injector().arm("lane_eviction", rate=0.25, times=2)
        try:
            for i in range(n_burst):
                p = a_prompt(a_len)
                if eng.should_shed(need_tokens=len(p) + 1):
                    shed += 1
                    continue
                rid = f"adm-{i}"
                eng.submit(GenerationRequest(
                    request_id=rid, prompt_ids=p,
                    sampling=SamplingParams(max_tokens=a_gen)))
                admitted.append(rid)
            while eng.has_work:
                eng.step()
        finally:
            get_injector().reset()
        res = [eng.poll(r) for r in admitted]
        clean = all(r is not None and r.finish_reason != "error"
                    and len(r.token_ids) == a_gen for r in res)
        del eng
        return len(admitted), shed, clean

    tier_admitted, tier_shed, tier_clean = run("tier")
    dev_admitted, dev_shed, dev_clean = run("device")
    log(f"tier admission: tier policy admitted {tier_admitted}/{n_burst} "
        f"(clean={tier_clean}) vs device-only {dev_admitted}/{n_burst} "
        f"at equal pool bytes")
    return {
        "tier_admission_lanes": tier_admitted,
        "tier_admission_shed": tier_shed,
        "tier_admission_clean": tier_clean,
        "device_admission_lanes": dev_admitted,
        "device_admission_shed": dev_shed,
    }


def long_prefill_leg(cfg, params) -> dict:
    """Flash paged prefill (ops/pallas_attention.flash_prefill_attention):
    flash-vs-dense TTFT at long prompt lengths, the chunked-vs-single-
    bucket crossover, a quantized-pool variant, and an analytic
    peak-live-bytes proxy for the attention intermediates.

    Dense prefill materializes the [S, T] score matrix and (on the chunk
    path) re-gathers the whole prefix every round, so its transient
    footprint grows with context; flash streams K/V pages through a
    fixed double-buffered VMEM window.  A dense leg is skipped — with
    the byte math recorded as the reason — when its analytic peak
    exceeds the dense budget (the paged pool bytes on TPU; relaxed by
    BENCH_PREFILL_DENSE_HEADROOM in the CPU dryrun so the short legs
    still measure dense while the longest leg exercises the same skip
    branch a 32k prompt does on the chip).  The longest flash-only leg
    is the served-where-dense-cannot evidence.

    ``BENCH_PREFILL_LENS`` / ``BENCH_PREFILL_CHUNK`` override the
    platform defaults (TPU: 2048,8192,32768 over a 512 chunk bucket;
    dryrun: 128,256,512 over 128 — interpret-mode Pallas is slow, so
    the dryrun lengths only validate the plumbing, not the speedup).
    """
    import numpy as np
    import jax

    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        GenerationRequest,
        InferenceEngine,
        SamplingParams,
    )

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    lens = tuple(int(x) for x in os.environ.get(
        "BENCH_PREFILL_LENS",
        "2048,8192,32768" if on_tpu else "128,256,512").split(","))
    gen = int(os.environ.get("BENCH_PREFILL_MAX_TOKENS", "4"))
    chunk_bucket = bucket64(int(os.environ.get(
        "BENCH_PREFILL_CHUNK", "512" if on_tpu else "128")))
    dense_headroom = float(os.environ.get(
        "BENCH_PREFILL_DENSE_HEADROOM", "1.0" if on_tpu else "5.0"))
    bs = 16
    kvh = cfg.num_kv_heads
    d = cfg.head_dim or cfg.hidden_size // cfg.num_heads
    rng = np.random.default_rng(11)

    def geometry(length: int) -> tuple[int, int]:
        cap = length + gen + 1
        bps = (cap + bs - 1) // bs
        return bps, bps + 17        # +17: null block + decode headroom

    def pool_bytes(length: int) -> int:
        _, nb = geometry(length)
        # f32 pool in the dryrun / bf16 on TPU; the proxy only needs the
        # two engines to agree, and they share one EngineConfig.
        el = 2 if on_tpu else 4
        return nb * bs * kvh * d * 2 * el

    def dense_peak_bytes(length: int) -> int:
        bps, _ = geometry(length)
        t_pad = bps * bs
        s_b = chunk_bucket if length > chunk_bucket else bucket64(length)
        # [1, H, S, T] f32 scores + the gathered [T, KVH, D] k/v pair
        # (f32 compute) — per layer, transient, but peak-live.
        return (cfg.num_heads * s_b * t_pad * 4
                + 2 * t_pad * kvh * d * 4)

    # Flash peak-live: double-buffered K and V window slabs in VMEM
    # (2 slots x W=8 pages x block_size tokens x head_dim lanes, f32).
    flash_window_bytes = 2 * 2 * (8 * bs) * d * 4

    def build(path: str, length: int, buckets, kv_dtype: str = "auto"):
        bps, nb = geometry(length)
        ecfg = EngineConfig(
            max_slots=2, num_blocks=nb, block_size=bs,
            max_blocks_per_seq=bps, prefill_buckets=buckets,
            max_prefills_per_step=1, max_admission_rounds=2,
            decode_steps_per_iter=2, prefix_cache_entries=0,
            prefill_path=path, kv_dtype=kv_dtype)
        return InferenceEngine(cfg, params, ecfg, eos_id=-1)

    def measure_ttft(eng, length: int, tag: str) -> float:
        prompt = [int(t) for t in
                  rng.integers(4, cfg.vocab_size - 4, size=length)]
        eng.generate([prompt], SamplingParams(max_tokens=2))  # warm compiles
        eng.submit(GenerationRequest(
            request_id=tag, prompt_ids=prompt,
            sampling=SamplingParams(max_tokens=gen)))
        while eng.has_work:
            eng.step()
        res = eng.poll(tag)
        assert res is not None and res.finish_reason != "error", tag
        return res.ttft_s * 1e3

    out: dict = {
        "prefill_lens": list(lens),
        "prefill_chunk_bucket": chunk_bucket,
        "prefill_dryrun": not on_tpu,
        "prefill_flash_vmem_window_bytes": flash_window_bytes,
    }
    speedup_at: dict[int, float] = {}
    for length in lens:
        buckets = ((chunk_bucket,) if length > chunk_bucket
                   else (bucket64(length),))
        eng_f = build("flash", length, buckets)
        assert eng_f.prefill_path == "flash", (
            "flash prefill not selected — leg would measure dense twice")
        f_ms = measure_ttft(eng_f, length, f"pf-flash-{length}")
        out[f"prefill_flash_ttft_ms_{length}"] = round(f_ms, 2)
        out[f"prefill_flash_buckets_{length}"] = list(
            eng_f.ecfg.prefill_buckets)
        del eng_f

        d_peak, pool = dense_peak_bytes(length), pool_bytes(length)
        out[f"prefill_dense_peak_bytes_{length}"] = d_peak
        out[f"prefill_pool_bytes_{length}"] = pool
        if d_peak > pool * dense_headroom:
            reason = (f"analytic dense peak {d_peak} B > "
                      f"{dense_headroom:g}x pool {pool} B")
            out[f"prefill_dense_skip_{length}"] = reason
            log(f"prefill leg {length}: flash {f_ms:.1f} ms; "
                f"dense SKIPPED ({reason})")
            continue
        eng_d = build("dense", length, buckets)
        d_ms = measure_ttft(eng_d, length, f"pf-dense-{length}")
        del eng_d
        ratio = d_ms / max(f_ms, 1e-9)
        speedup_at[length] = ratio
        out[f"prefill_dense_ttft_ms_{length}"] = round(d_ms, 2)
        out[f"prefill_speedup_{length}"] = round(ratio, 3)
        log(f"prefill leg {length}: flash {f_ms:.1f} ms vs dense "
            f"{d_ms:.1f} ms ({ratio:.2f}x)")

    if speedup_at:
        top = max(speedup_at)
        out["prefill_speedup_max_len"] = round(speedup_at[top], 3)
        out["prefill_speedup_max_len_tokens"] = top

    # Chunked-vs-single-bucket crossover: first length long enough to
    # chunk but short enough that the flash bucket auto-extension can't
    # lift it back to a single round (capacity < 4096 tokens).
    lx = next((n for n in lens
               if n > chunk_bucket and n + gen + 1 + bs < 4096), None)
    if lx is not None:
        eng_s = build("flash", lx, (bucket64(lx),))
        s_ms = measure_ttft(eng_s, lx, f"pf-single-{lx}")
        del eng_s
        c_ms = out[f"prefill_flash_ttft_ms_{lx}"]
        out["prefill_crossover_len"] = lx
        out["prefill_single_bucket_ttft_ms"] = round(s_ms, 2)
        out["prefill_chunked_ttft_ms"] = c_ms
        out["prefill_crossover_winner"] = (
            "single" if s_ms <= c_ms else "chunked")
        log(f"prefill crossover @{lx}: single-bucket {s_ms:.1f} ms vs "
            f"chunked {c_ms:.1f} ms")

    # Quantized-pool variant: in-kernel dequant from the int8 pool.
    lq = lens[0]
    try:
        eng_q = build("flash", lq, (bucket64(lq),), kv_dtype="int8")
        q_ms = measure_ttft(eng_q, lq, f"pf-quant-{lq}")
        del eng_q
        out["prefill_quant_flash_ttft_ms"] = round(q_ms, 2)
        log(f"prefill quant (int8 pool) @{lq}: flash {q_ms:.1f} ms")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"prefill quant variant skipped: {exc}")
    return out


def main() -> None:
    t0 = time.monotonic()
    cache_was_warm = CACHE_DIR.is_dir() and any(CACHE_DIR.iterdir())
    import numpy as np
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # The environment's sitecustomize re-pins jax_platforms to the real
        # chip; honor an explicit JAX_PLATFORMS (CPU smoke runs) over it.
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    jax.config.update("jax_compilation_cache_dir", str(CACHE_DIR))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import PRESETS
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        GenerationRequest,
        InferenceEngine,
        SamplingParams,
    )
    from k8s_llm_monitor_tpu.utils import quantize as qz

    model_name = os.environ.get("BENCH_MODEL", "llama3-8b")
    quant = os.environ.get("BENCH_QUANT", "int8")
    n_requests = int(os.environ.get("BENCH_CONCURRENCY", "100"))
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN", "192"))
    max_tokens = int(os.environ.get("BENCH_MAX_TOKENS", "48"))

    cfg = PRESETS[model_name]
    dev = jax.devices()[0]
    flops_peak, hbm_peak = chip_peaks(dev.device_kind)
    log(f"bench: {model_name} ({quant}) on {dev.platform}:{dev.device_kind} "
        f"({n_requests} concurrent, prompt {prompt_len}, gen {max_tokens})")

    if quant == "int8":
        params = qz.init_params_quantized(jax.random.PRNGKey(0), cfg)
    else:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    weight_elems, stream_bytes = weight_accounting(params, cfg.tie_embeddings)
    weight_bytes = qz.param_bytes(params)
    log(f"weights: {weight_elems/1e9:.2f}B matmul params, "
        f"{weight_bytes/2**30:.2f} GiB on device")

    if os.environ.get("BENCH_FLEET_ONLY", "0") == "1":
        # Fast CPU-only fleet smoke for `make bench-fleet`: skips the ~12
        # main legs and runs just the 1-vs-2-replica router comparison.
        stats = fleet_leg(cfg, params)
        print(json.dumps({
            "metric": "fleet_2replica_tok_s",
            "value": stats.get("fleet_2replica_tok_s", 0.0),
            "unit": "tok/s",
            "extras": {"model": model_name, "platform": dev.platform,
                       **stats},
        }))
        return

    if os.environ.get("BENCH_TENANT_ONLY", "0") == "1":
        # `make bench-tenant`: just the multi-tenant fairness leg — a
        # flooding tenant rate-limited with tenant-tagged 429s while
        # quiet Zipf tenants stay byte-exact within 2x their solo TTFT.
        stats = tenant_fairness_leg(cfg, params)
        print(json.dumps({
            "metric": "tenant_interactive_p99_ttft_ratio",
            "value": stats.get("tenant_interactive_p99_ttft_ratio", 0.0),
            "unit": "x",
            "extras": {"model": model_name, "platform": dev.platform,
                       **stats},
        }))
        return

    if os.environ.get("BENCH_REMEDIATE_ONLY", "0") == "1":
        # `make bench-remediate`: the closed-loop remediation leg —
        # inject->verified-recovery latency for all four chaos scenarios
        # plus the plan-constrained-decode overhead gate (< 10% tok/s).
        stats = remediation_leg(cfg, params)
        print(json.dumps({
            "metric": "remediation_plan_overhead_pct",
            "value": stats.get("remediation_plan_overhead_pct", 0.0),
            "unit": "%",
            "extras": {"model": model_name, "platform": dev.platform,
                       **stats},
        }))
        return

    if os.environ.get("BENCH_SIGNALS_ONLY", "0") == "1":
        # `make bench-signals`: just the telemetry-plane overhead leg
        # (CPU-friendly; asserts the < 1% tok/s scraper budget).
        stats = signals_leg(cfg, params)
        print(json.dumps({
            "metric": "signals_overhead_pct",
            "value": stats.get("signals_overhead_pct", 0.0),
            "unit": "%",
            "extras": {"model": model_name, "platform": dev.platform,
                       **stats},
        }))
        return

    if os.environ.get("BENCH_ELASTIC_ONLY", "0") == "1":
        # `make bench-elastic`: scale-up reaction time, churn-vs-steady
        # TTFT tail, and the handoff-vs-cold-prefill ratio (budget 0.5x).
        stats = elasticity_leg(cfg, params)
        print(json.dumps({
            "metric": "elastic_handoff_vs_local_ttft",
            "value": stats.get("elastic_handoff_vs_local_ttft", 0.0),
            "unit": "x",
            "extras": {"model": model_name, "platform": dev.platform,
                       **stats},
        }))
        return

    if os.environ.get("BENCH_PREFILL_ONLY", "0") == "1":
        # `make bench-prefill`: flash-vs-dense long-prefill TTFT, the
        # chunked-vs-single-bucket crossover, and the longest flash-only
        # length the dense path's transient footprint cannot serve.
        stats = long_prefill_leg(cfg, params)
        print(json.dumps({
            "metric": "prefill_flash_vs_dense_ttft",
            "value": stats.get("prefill_speedup_max_len", 0.0),
            "unit": "x",
            "extras": {"model": model_name, "platform": dev.platform,
                       **stats},
        }))
        return

    if os.environ.get("BENCH_MESH_ONLY", "0") == "1":
        # `make bench-mesh`: just the TP-mesh leg.  Dryrun on the forced
        # 8-host-device CPU mesh in CI; measured on a real slice.
        stats = mesh_leg(cfg, params)
        try:
            stats.update(overlap_leg(cfg, params))
        except Exception as exc:  # noqa: BLE001 — extras never fail the bench
            log(f"overlap leg skipped: {exc}")
        try:
            stats.update(tier_admission_leg(cfg, params))
        except Exception as exc:  # noqa: BLE001
            log(f"tier admission leg skipped: {exc}")
        print(json.dumps({
            "metric": "mesh_tok_s",
            "value": stats.get("mesh_tok_s", 0.0),
            "unit": "tok/s",
            "extras": {"model": model_name, "platform": dev.platform,
                       **stats},
        }))
        return

    # Prompt bucket hugs the prompt length (rounded to the 64-lane sublane
    # multiple; 192 itself is 1.5 * 128 and MXU-friendly): minimal padding
    # waste in the prefill calls that dominate TTFT.
    bucket = bucket64(prompt_len)
    seq_cap = prompt_len + max_tokens + 1
    # Shared-prefix leg geometry: diagnosis queries share the system
    # preamble + evidence prefix (monitor/analysis.py), modeled as 2/3 of
    # the prompt; the suffix bucket keeps hit-round prefills suffix-sized.
    shared_len = int(os.environ.get(
        "BENCH_SHARED_PREFIX_LEN", str((2 * prompt_len // 3) // 16 * 16)))
    suffix_bucket = bucket64(max(prompt_len - shared_len, 16))
    ecfg = EngineConfig(
        max_slots=int(os.environ.get("BENCH_SLOTS", "128")),
        num_blocks=int(os.environ.get("BENCH_BLOCKS", "2200")),
        block_size=16,
        max_blocks_per_seq=(seq_cap + 15) // 16,
        prefill_buckets=tuple(sorted({suffix_bucket, bucket})),
        max_prefills_per_step=int(os.environ.get("BENCH_PREFILL_BATCH", "16")),
        max_admission_rounds=8,
        decode_steps_per_iter=int(os.environ.get("BENCH_DECODE_STEPS", "8")),
    )
    eng = InferenceEngine(cfg, params, ecfg, eos_id=-1)

    rng = np.random.default_rng(0)

    def prompt() -> list[int]:
        return list(rng.integers(4, cfg.vocab_size - 4, size=prompt_len))

    def ttft_pcts(results) -> tuple[float, float]:
        """(p50, p99) TTFT in ms — every leg reports its tail, not just the
        headline (a diagnosis product's slowest 1% is a budget, not noise)."""
        t = np.array(sorted(r.ttft_s for r in results))
        return (float(np.percentile(t, 50)) * 1e3,
                float(np.percentile(t, 99)) * 1e3)

    # Warm up every compiled shape — the power-of-two admission-lane ladder
    # (the engine pads prefill batches up, so a 100-burst walks P=16 rounds
    # plus a P=4 tail) and the fused-decode K ladder the drain will walk —
    # so the measured run excludes compile time.  With a populated
    # .jax_cache this is seconds, not minutes.
    log(f"warmup (compiles prefill/decode; cache "
        f"{'warm' if cache_was_warm else 'cold'})...")
    wt0 = time.monotonic()
    eng.generate([prompt() for _ in range(ecfg.max_prefills_per_step)],
                 SamplingParams(max_tokens=max_tokens))
    w = ecfg.max_prefills_per_step // 2
    while w >= 1:
        eng.generate([prompt() for _ in range(w)],
                     SamplingParams(max_tokens=4))
        w //= 2
    warmup_s = time.monotonic() - wt0
    log(f"warmup done in {warmup_s:.1f}s")

    # --- headline: concurrent burst, all requests queued at t=0 ---------
    bt0 = time.monotonic()
    for i in range(n_requests):
        eng.submit(GenerationRequest(
            request_id=f"bench-{i}",
            prompt_ids=prompt(),
            sampling=SamplingParams(max_tokens=max_tokens),
        ))
    steps0, prefills0 = eng.steps, eng.prefills
    while eng.has_work:
        eng.step()
    wall = time.monotonic() - bt0

    results = [eng.poll(f"bench-{i}") for i in range(n_requests)]
    assert all(r is not None and r.finish_reason != "error" for r in results)
    steps_run, prefills_run = eng.steps - steps0, eng.prefills - prefills0
    preempts = eng.preemptions
    ttfts = np.array(sorted(r.ttft_s for r in results))
    total_tokens = sum(len(r.token_ids) for r in results)
    p50 = float(np.percentile(ttfts, 50))
    p99 = float(np.percentile(ttfts, 99))
    toks_per_s = total_tokens / wall

    log(f"drained {n_requests} requests in {wall:.2f}s "
        f"({steps_run} steps, {prefills_run} prefills, "
        f"{preempts} preemptions)")
    log(f"p50 TTFT {p50 * 1e3:.1f} ms | p99 {p99 * 1e3:.1f} ms | "
        f"throughput {toks_per_s:.0f} tok/s")

    # --- per-chip-equivalent leg: the SLO's v5e-8 config spread over 8
    # chips is ~12 concurrent per chip; same engine, warm shapes. ---------
    perchip_p50_ms = perchip_p99_ms = None
    try:
        n_pc = max(1, n_requests // 8)
        for i in range(n_pc):
            eng.submit(GenerationRequest(
                request_id=f"pc-{i}", prompt_ids=prompt(),
                sampling=SamplingParams(max_tokens=max_tokens)))
        while eng.has_work:
            eng.step()
        pcres = [eng.poll(f"pc-{i}") for i in range(n_pc)]
        assert all(r is not None and r.finish_reason != "error" for r in pcres)
        perchip_p50_ms, perchip_p99_ms = ttft_pcts(pcres)
        log(f"per-chip-equivalent ({n_pc} concurrent, informational — "
            f"see mesh leg for the measured multi-chip number): "
            f"p50 TTFT {perchip_p50_ms:.1f} ms, p99 {perchip_p99_ms:.1f} ms")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"per-chip leg skipped: {exc}")

    # --- shared-prefix leg: the realistic diagnosis workload — all queries
    # share the preamble+evidence prefix, prefilled once via the prefix
    # cache (suffix-only chunked admission).  Warm pass first so compile
    # time for the suffix-bucket program stays out of the measurement. ----
    shared_p50_ms = shared_p99_ms = None
    try:
        pre = prompt()[:shared_len]

        def shared_prompt() -> list[int]:
            return pre + list(rng.integers(
                4, cfg.vocab_size - 4, size=prompt_len - shared_len))

        # Seed the cache first (a lone request registers the prefix), THEN
        # warm the batched chunked-prefill program at every ladder lane
        # count a draining burst can hit — hits in the same round as the
        # seed would run the dense path and leave the chunked programs to
        # compile inside the measurement.
        eng.generate([shared_prompt()], SamplingParams(max_tokens=4))
        w = 2
        while w <= ecfg.max_prefills_per_step:
            eng.generate([shared_prompt() for _ in range(w)],
                         SamplingParams(max_tokens=4))
            w *= 2
        st0 = time.monotonic()
        for i in range(n_requests):
            eng.submit(GenerationRequest(
                request_id=f"sh-{i}", prompt_ids=shared_prompt(),
                sampling=SamplingParams(max_tokens=max_tokens)))
        while eng.has_work:
            eng.step()
        swall = time.monotonic() - st0
        sres = [eng.poll(f"sh-{i}") for i in range(n_requests)]
        assert all(r is not None and r.finish_reason != "error" for r in sres)
        shared_p50_ms, shared_p99_ms = ttft_pcts(sres)
        pc = eng.prefix_cache
        log(f"shared-prefix ({shared_len}/{prompt_len} tokens cached): "
            f"p50 TTFT {shared_p50_ms:.1f} ms, p99 {shared_p99_ms:.1f} ms, "
            f"drained in {swall:.2f}s "
            f"(cache hits {pc.hits}, misses {pc.misses})")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"shared-prefix leg skipped: {exc}")

    # --- SLO-class leg: the same burst with classes attached (round-robin
    # interactive/standard/batch).  Class scheduling sorts admission and
    # evicts batch lanes for interactive arrivals, so interactive must hold
    # a tight tail (p99 <= 2x p50) while batch absorbs the queueing. ------
    slo_class_stats = None
    try:
        slo_classes = ("interactive", "standard", "batch")
        slo_rids = []
        for i in range(n_requests):
            c = slo_classes[i % len(slo_classes)]
            rid = f"slo-{i}"
            slo_rids.append((rid, c))
            eng.submit(GenerationRequest(
                request_id=rid, prompt_ids=prompt(),
                sampling=SamplingParams(max_tokens=max_tokens),
                slo_class=c))
        while eng.has_work:
            eng.step()
        by_class: dict[str, list] = {c: [] for c in slo_classes}
        for rid, c in slo_rids:
            r = eng.poll(rid)
            assert r is not None and r.finish_reason != "error"
            by_class[c].append(r)
        slo_class_stats = {}
        for c in slo_classes:
            c_p50, c_p99 = ttft_pcts(by_class[c])
            slo_class_stats[c] = {"p50_ttft_ms": round(c_p50, 2),
                                  "p99_ttft_ms": round(c_p99, 2),
                                  "n": len(by_class[c])}
        ia = slo_class_stats["interactive"]
        ia["p99_over_p50"] = round(
            ia["p99_ttft_ms"] / max(ia["p50_ttft_ms"], 1e-9), 2)
        ia["tail_ok"] = ia["p99_ttft_ms"] <= 2.0 * ia["p50_ttft_ms"]
        for c in slo_classes:
            s = slo_class_stats[c]
            log(f"slo-class {c}: p50 TTFT {s['p50_ttft_ms']:.1f} ms, "
                f"p99 {s['p99_ttft_ms']:.1f} ms ({s['n']} reqs)")
        log(f"interactive tail under mixed-class burst: "
            f"p99/p50 = {ia['p99_over_p50']:.2f}x "
            f"({'OK' if ia['tail_ok'] else 'OVER'} budget 2.00x)")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"slo-class leg skipped: {exc}")

    # --- utilization micro-legs on the warm compiled programs -----------
    prefill_tflops = prefill_mfu = 0.0
    decode_gbs = decode_bw_util = 0.0
    try:
        import jax.numpy as jnp

        P = ecfg.max_prefills_per_step
        S = ecfg.prefill_buckets[-1]
        toks = jnp.asarray(rng.integers(4, cfg.vocab_size - 4,
                                        size=(P, S)), jnp.int32)
        lengths = jnp.full((P,), S, jnp.int32)
        blocks_per = min((S + 15) // 16, ecfg.max_blocks_per_seq)
        tbl = np.zeros((P, ecfg.max_blocks_per_seq), np.int32)
        for j in range(P):
            lo = 1 + j * blocks_per
            tbl[j, :blocks_per] = np.arange(lo, lo + blocks_per)
        tbl = jnp.asarray(tbl)
        # Warm (already compiled by the engine) — time reps.
        first, eng.pages = eng._prefill_greedy(
            params, toks, lengths, eng.pages, tbl)
        first.block_until_ready()
        reps = 3
        pt0 = time.monotonic()
        for _ in range(reps):
            first, eng.pages = eng._prefill_greedy(
                params, toks, lengths, eng.pages, tbl)
        first.block_until_ready()
        pdt = time.monotonic() - pt0
        # Dense-matmul FLOPs dominate; attention terms are <2% at S=192.
        prefill_tflops = reps * 2.0 * weight_elems * P * S / pdt / 1e12
        if flops_peak:
            prefill_mfu = prefill_tflops * 1e12 / flops_peak
        log(f"prefill: {prefill_tflops:.1f} TFLOP/s"
            + (f" ({prefill_mfu * 100:.0f}% MFU)" if flops_peak else ""))

        # Decode: each fused step streams the full weight set once.
        K = ecfg.decode_steps_per_iter
        prog = eng._decode_program(K, sampled=False)
        B = ecfg.max_slots
        ctx = jnp.full((B,), prompt_len, jnp.int32)
        remaining = jnp.full((B,), 10 ** 6, jnp.int32)
        dtbl = jnp.asarray(np.tile(tbl[:1], (B, 1)))
        eos = jnp.asarray(-1, jnp.int32)
        tok_state = jnp.zeros((B,), jnp.int32)
        _, tok_state, eng.pages = prog(params, tok_state, ctx, remaining,
                                       eng.pages, dtbl, eos)
        tok_state.block_until_ready()
        dt0 = time.monotonic()
        for _ in range(reps):
            _, tok_state, eng.pages = prog(
                params, tok_state, ctx, remaining, eng.pages, dtbl, eos)
        tok_state.block_until_ready()
        ddt = time.monotonic() - dt0
        decode_gbs = reps * K * stream_bytes / ddt / 1e9
        if hbm_peak:
            decode_bw_util = decode_gbs * 1e9 / hbm_peak
        step_ms = ddt / (reps * K) * 1e3
        # Attribution of the sub-50% HBM utilization: at B lanes the step
        # sits at the compute/bandwidth RIDGE — streaming the int8 weights
        # is only part of the time; the dequantized bf16 matmul at B rows
        # costs about as much again (plus attention/dispatch residue), so
        # the step is not HBM-bound and can't reach the bandwidth ceiling.
        # (Measured v5e: B=8 14.1 ms/step vs B=128 28.2 — the growth is
        # the B-scaled matmul term; W8A8's s8xs8 matmul cuts it to 24.1.)
        stream_ms = stream_bytes / hbm_peak * 1e3 if hbm_peak else 0.0
        matmul_ms = (2.0 * weight_elems * B / flops_peak * 1e3
                     if flops_peak else 0.0)
        decode_step_ms, decode_stream_ms, decode_matmul_ms = (
            step_ms, stream_ms, matmul_ms)
        log(f"decode weight traffic: {decode_gbs:.0f} GB/s"
            + (f" ({decode_bw_util * 100:.0f}% of HBM)" if hbm_peak else "")
            + f" [{B} lanes -> {B * reps * K / ddt:.0f} tok/s ceiling]")
        log(f"decode step attribution ({B} lanes): {step_ms:.1f} ms/step = "
            f"weight stream {stream_ms:.1f} + bf16 matmul ~{matmul_ms:.1f} "
            f"+ residual {max(step_ms - stream_ms - matmul_ms, 0):.1f} "
            f"(compute/bandwidth ridge, not HBM-bound)")
    except Exception as exc:  # noqa: BLE001
        decode_step_ms = decode_stream_ms = decode_matmul_ms = None
        log(f"utilization legs skipped: {exc}")

    # --- fused-vs-fallback decode micro-leg: the Pallas fused kernel
    # (in-kernel RoPE + KV append + paged attention) against the XLA
    # gather path, same K-step greedy scan, same synthetic state.  Greedy
    # token streams must match — the fallback is the fused kernel's
    # numerics oracle.  TPU-only: interpret-mode Pallas inside a scan is
    # pathological on CPU and would time the emulator, not the kernel. ---
    fused_decode_step_ms = fallback_decode_step_ms = None
    fused_match = None
    try:
        import jax.numpy as jnp

        if dev.platform != "tpu":
            raise RuntimeError(f"needs TPU (platform={dev.platform})")
        from k8s_llm_monitor_tpu.ops.attention import select_decode_impl

        impls = {
            "fallback": select_decode_impl(cfg=cfg, mode="gather"),
            "fused": select_decode_impl(cfg=cfg, mode="fused"),
        }
        K = ecfg.decode_steps_per_iter
        B = ecfg.max_slots

        def _make_prog(impl):
            def fn(params, tok_state, ctx, pages, tables):
                def body(carry, _):
                    tokens, c, pages = carry
                    logits, pages = llama.decode_step(
                        params, cfg, tokens, c, pages, tables,
                        attn_impl=impl)
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                    return (nxt, c + 1, pages), nxt
                (tok_state, _, pages), toks = jax.lax.scan(
                    body, (tok_state, ctx, pages),
                    jnp.arange(K, dtype=jnp.int32))
                return toks, tok_state, pages
            return jax.jit(fn, donate_argnums=(3,))

        ctx = jnp.full((B,), prompt_len, jnp.int32)
        dtbl = jnp.asarray(np.tile(np.asarray(tbl)[:1], (B, 1)))
        streams = {}
        times = {}
        reps = 3
        for name, impl in impls.items():
            prog = _make_prog(impl)
            tok_state = jnp.zeros((B,), jnp.int32)
            toks, tok_state, eng.pages = prog(
                params, tok_state, ctx, eng.pages, dtbl)
            streams[name] = np.asarray(toks)
            ft0 = time.monotonic()
            for _ in range(reps):
                _, tok_state, eng.pages = prog(
                    params, jnp.zeros((B,), jnp.int32), ctx,
                    eng.pages, dtbl)
            tok_state.block_until_ready()
            times[name] = (time.monotonic() - ft0) / (reps * K) * 1e3
        fused_decode_step_ms = times["fused"]
        fallback_decode_step_ms = times["fallback"]
        fused_match = bool(
            np.array_equal(streams["fused"], streams["fallback"]))
        log(f"fused decode kernel: {fused_decode_step_ms:.2f} ms/step vs "
            f"gather fallback {fallback_decode_step_ms:.2f} ms/step "
            f"({fallback_decode_step_ms / max(fused_decode_step_ms, 1e-9):.2f}x)"
            f" | greedy streams identical: {fused_match}")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"fused-vs-fallback leg skipped: {exc}")

    # --- decode phase attribution: attention vs sampling share of the
    # step, measured on the engine's own warm programs; populates the
    # decode_attn_ms / decode_sample_ms exporter gauges. ----------------
    decode_phases = None
    try:
        decode_phases = eng.profile_decode_phases()
        log(f"decode phases: attn {decode_phases['decode_attn_ms']:.2f} ms"
            f" + sample {decode_phases['decode_sample_ms']:.2f} ms of "
            f"{decode_phases['decode_step_ms_long_ctx']:.2f} ms/step "
            f"(long-ctx)")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"decode phase attribution skipped: {exc}")
    # Captured now: the headline engine is deleted before extras assembly.
    decode_path = eng.decode_path
    decode_host_gap_ms = eng.decode_host_gap_ms

    # --- E2E 128-lane decode saturation: short prompts, generations that
    # fill each lane's KV capacity, all max_slots lanes live — the engine
    # (scheduler + reconcile + fused dispatch) at the lane count the
    # micro-leg ceiling is quoted for. ---------------------------------
    dec_e2e_tok_s = None
    try:
        n_dec = ecfg.max_slots
        dplen = 64
        dgen = eng.capacity_tokens - dplen - 1
        def dec_prompt() -> list[int]:
            return list(rng.integers(4, cfg.vocab_size - 4, size=dplen))
        # Warm the short-prompt bucket's admission ladder.
        w = ecfg.max_prefills_per_step
        while w >= 1:
            eng.generate([dec_prompt() for _ in range(w)],
                         SamplingParams(max_tokens=4))
            w //= 2
        dt0 = time.monotonic()
        for i in range(n_dec):
            eng.submit(GenerationRequest(
                request_id=f"dec-{i}", prompt_ids=dec_prompt(),
                sampling=SamplingParams(max_tokens=dgen)))
        while eng.has_work:
            eng.step()
        dwall = time.monotonic() - dt0
        dres = [eng.poll(f"dec-{i}") for i in range(n_dec)]
        assert all(r is not None and r.finish_reason != "error" for r in dres)
        dtoks = sum(len(r.token_ids) for r in dres)
        dec_e2e_tok_s = dtoks / dwall
        ceiling = (f" vs {B * reps * K / ddt:.0f} tok/s fused-step ceiling"
                   if decode_gbs else "")  # micro-leg may have been skipped
        log(f"E2E decode saturation ({n_dec} lanes x {dgen} tokens): "
            f"{dec_e2e_tok_s:.0f} tok/s engine{ceiling}")
    except Exception as exc:  # noqa: BLE001
        log(f"decode saturation leg skipped: {exc}")
    del eng  # free the headline KV pool before the long-prompt engine

    # --- mesh leg: TP over every local device — the SLO's actual v5e-8
    # shape, measured.  Supersedes the per-chip-equivalence arithmetic
    # above (kept in extras as informational only).  Runs after the
    # headline engine is freed so the sharded weight copies fit. ---------
    mesh_stats = {}
    if len(jax.devices()) > 1 and os.environ.get("BENCH_MESH", "1") == "1":
        try:
            mesh_stats = mesh_leg(cfg, params)
        except Exception as exc:  # noqa: BLE001 — extras never fail the bench
            log(f"mesh leg skipped: {exc}")
        try:
            mesh_stats.update(overlap_leg(cfg, params))
        except Exception as exc:  # noqa: BLE001
            log(f"overlap leg skipped: {exc}")

    # --- W8A8 leg: dynamic per-token activation int8 on top of the int8
    # weights — prefill runs s8 x s8 on the MXU int8 path (measured ~203
    # vs ~145 TFLOP/s bf16 on dense [4,512] prefill, ~1.4x).  Same weights pytree, separate engine/compile.
    # Parity contract: tests/test_quantize.py::test_w8a8_forward_parity. --
    w8a8_p50_ms = w8a8_perchip_p50_ms = w8a8_shared_p50_ms = None
    w8a8_p99_ms = w8a8_perchip_p99_ms = None
    w8a8_shared_p99_ms = w8a8_decode_tok_s = None
    cold_shared_p50_ms = cold_shared_p99_ms = None
    w8a8_wall = 0.0
    if quant == "int8" and os.environ.get("BENCH_W8A8", "1") == "1":
        aeng = None
        try:
            import dataclasses as _dc

            cfg_aq = _dc.replace(cfg, act_quant=True)
            aeng = InferenceEngine(cfg_aq, params, ecfg, eos_id=-1)
            aeng.generate([prompt() for _ in range(ecfg.max_prefills_per_step)],
                          SamplingParams(max_tokens=max_tokens))
            w = ecfg.max_prefills_per_step // 2
            while w >= 1:
                aeng.generate([prompt() for _ in range(w)],
                              SamplingParams(max_tokens=4))
                w //= 2
            at0 = time.monotonic()
            for i in range(n_requests):
                aeng.submit(GenerationRequest(
                    request_id=f"aq-{i}", prompt_ids=prompt(),
                    sampling=SamplingParams(max_tokens=max_tokens)))
            while aeng.has_work:
                aeng.step()
            w8a8_wall = time.monotonic() - at0
            ares = [aeng.poll(f"aq-{i}") for i in range(n_requests)]
            assert all(r is not None and r.finish_reason != "error"
                       for r in ares)
            w8a8_p50_ms, w8a8_p99_ms = ttft_pcts(ares)
            n_pc = max(1, n_requests // 8)
            for i in range(n_pc):
                aeng.submit(GenerationRequest(
                    request_id=f"aqpc-{i}", prompt_ids=prompt(),
                    sampling=SamplingParams(max_tokens=max_tokens)))
            while aeng.has_work:
                aeng.step()
            apc = [aeng.poll(f"aqpc-{i}") for i in range(n_pc)]
            assert all(r is not None and r.finish_reason != "error"
                       for r in apc)
            w8a8_perchip_p50_ms, w8a8_perchip_p99_ms = ttft_pcts(apc)
            log(f"W8A8: p50 TTFT {w8a8_p50_ms:.1f} ms, p99 "
                f"{w8a8_p99_ms:.1f} ms at {n_requests} "
                f"concurrent (drained {w8a8_wall:.2f}s); per-chip-equiv "
                f"{w8a8_perchip_p50_ms:.1f} ms")

            # W8A8 + shared prefix: the realistic diagnosis shape at the
            # full 100-concurrent load on ONE chip.
            pre2 = prompt()[:shared_len]

            def w8a8_shared() -> list[int]:
                return pre2 + list(rng.integers(
                    4, cfg.vocab_size - 4, size=prompt_len - shared_len))
            aeng.generate([w8a8_shared()], SamplingParams(max_tokens=4))
            w = 2
            while w <= ecfg.max_prefills_per_step:
                aeng.generate([w8a8_shared() for _ in range(w)],
                              SamplingParams(max_tokens=4))
                w *= 2
            for i in range(n_requests):
                aeng.submit(GenerationRequest(
                    request_id=f"aqsh-{i}", prompt_ids=w8a8_shared(),
                    sampling=SamplingParams(max_tokens=max_tokens)))
            while aeng.has_work:
                aeng.step()
            ash = [aeng.poll(f"aqsh-{i}") for i in range(n_requests)]
            assert all(r is not None and r.finish_reason != "error"
                       for r in ash)
            w8a8_shared_p50_ms, w8a8_shared_p99_ms = ttft_pcts(ash)
            log(f"W8A8 shared-prefix: p50 TTFT {w8a8_shared_p50_ms:.1f} ms, "
                f"p99 {w8a8_shared_p99_ms:.1f} ms "
                f"at {n_requests} concurrent")

            # COLD shared prefix: same shape, but the cache has never seen
            # the prefix and nothing is pre-seeded — the first queries
            # after a fresh snapshot.  Admission's cold-burst dedup
            # (serving/engine.py _admit_round) must prefill the prefix
            # once, not once per round-1 lane; every compiled program is
            # already warm, so the delta vs the seeded leg above is pure
            # scheduling.
            pre_cold = list(rng.integers(4, cfg.vocab_size - 4,
                                         size=shared_len))

            def w8a8_cold() -> list[int]:
                return pre_cold + list(rng.integers(
                    4, cfg.vocab_size - 4, size=prompt_len - shared_len))
            defer0 = aeng.prefix_deferrals
            miss0 = aeng.prefix_cache.misses
            for i in range(n_requests):
                aeng.submit(GenerationRequest(
                    request_id=f"aqcold-{i}", prompt_ids=w8a8_cold(),
                    sampling=SamplingParams(max_tokens=max_tokens)))
            while aeng.has_work:
                aeng.step()
            acold = [aeng.poll(f"aqcold-{i}") for i in range(n_requests)]
            assert all(r is not None and r.finish_reason != "error"
                       for r in acold)
            cold_shared_p50_ms, cold_shared_p99_ms = ttft_pcts(acold)
            log(f"W8A8 COLD shared-prefix: p50 TTFT "
                f"{cold_shared_p50_ms:.1f} ms, p99 "
                f"{cold_shared_p99_ms:.1f} ms at {n_requests} concurrent "
                f"[{aeng.prefix_deferrals - defer0} deferrals, "
                f"{aeng.prefix_cache.misses - miss0} full-prefix misses]")

            # W8A8 fused-decode step rate at full lanes: the s8 x s8
            # matmul halves the compute term of the decode-step ridge
            # (see the attribution print above), so the serving-default
            # quant mode wins decode too, not just prefill.
            import jax.numpy as jnp

            Kd, Bd = ecfg.decode_steps_per_iter, ecfg.max_slots
            prog = aeng._decode_program(Kd, sampled=False)
            blocks_per = min((prompt_len + 16 + 15) // 16,
                             ecfg.max_blocks_per_seq)
            wtbl = np.zeros((Bd, ecfg.max_blocks_per_seq), np.int32)
            wtbl[:, :blocks_per] = np.arange(1, blocks_per + 1)[None, :]
            wtbl = jnp.asarray(wtbl)
            wctx = jnp.full((Bd,), prompt_len, jnp.int32)
            wrem = jnp.full((Bd,), 10 ** 6, jnp.int32)
            weos = jnp.asarray(-1, jnp.int32)
            wtok = jnp.zeros((Bd,), jnp.int32)
            _, wtok, aeng.pages = prog(params, wtok, wctx, wrem,
                                       aeng.pages, wtbl, weos)
            _ = int(wtok[0])
            wreps = 3
            wt0 = time.monotonic()
            for _ in range(wreps):
                _, wtok, aeng.pages = prog(params, wtok, wctx, wrem,
                                           aeng.pages, wtbl, weos)
            _ = int(wtok[0])
            wddt = time.monotonic() - wt0
            w8a8_decode_tok_s = Bd * wreps * Kd / wddt
            log(f"W8A8 decode: {wddt / (wreps * Kd) * 1e3:.1f} ms/step "
                f"-> {w8a8_decode_tok_s:.0f} tok/s at {Bd} lanes")
        except Exception as exc:  # noqa: BLE001 — extras never fail the bench
            log(f"W8A8 leg skipped: {exc}")
        finally:
            del aeng  # free its KV pool before the long-prompt engine

    # Long-prompt leg: realistic multi-KB diagnosis prompts exercising
    # chunked prefill (prompts > the largest bucket), so the headline number
    # can't hide a slow chunk path.  Separate engine so bucket shapes and the
    # KV pool match the longer sequences.
    long_p50_ms = long_p99_ms = None  # omitted if the leg doesn't complete
    long_shared_p50_ms = long_shared_p99_ms = None
    long_shared_perchip_p50_ms = None
    long_perchip_p50_ms = None
    try:
        n_long = int(os.environ.get("BENCH_LONG_CONCURRENCY", "16"))
        long_len = int(os.environ.get("BENCH_LONG_PROMPT_LEN", "1536"))
        lcfg = EngineConfig(
            max_slots=16,
            num_blocks=1700,
            block_size=16,
            max_blocks_per_seq=128,
            # 512 = the chunk width (measured optimal vs 768/1024); 256 =
            # the shared-prefix suffix bucket — without it the 256-token
            # suffix admissions pad to 512 (2x FLOPs; measured 549 ->
            # 310-345 ms p50 on the shared-prefix leg).
            prefill_buckets=(256, 512),
            max_prefills_per_step=4,
            max_admission_rounds=4,
            decode_steps_per_iter=8,
            # Prefill-priority for the burst: with 12 chunk rounds queued,
            # decode interleaves steal first-token bandwidth — 6 (vs the
            # default 3) measured 1.42s -> 1.30s p50 AND a faster drain
            # (2.92 -> 2.73s wall) at 16 concurrent long prompts.
            decode_every_n_chunk_rounds=6,
        )
        # Long-prompt chunks are pure prefill compute — run them W8A8
        # (same parity contract as the headline W8A8 leg) when the weights
        # are int8; extras record the mode.
        import dataclasses as _dc

        long_cfg = (_dc.replace(cfg, act_quant=True)
                    if quant == "int8" else cfg)
        leng = InferenceEngine(long_cfg, params, lcfg, eos_id=-1)

        def long_prompt() -> list[int]:
            return list(rng.integers(4, cfg.vocab_size - 4, size=long_len))

        # Warm the chunk-round lane ladder (P=1/2/4; the per-chip leg runs
        # 2 lanes) + the decode K ladder (max_tokens=16 walks K=8,4,2,1).
        leng.generate([long_prompt()], SamplingParams(max_tokens=16))
        leng.generate([long_prompt() for _ in range(2)],
                      SamplingParams(max_tokens=16))
        leng.generate([long_prompt() for _ in range(4)],
                      SamplingParams(max_tokens=16))
        lt0 = time.monotonic()
        for i in range(n_long):
            leng.submit(GenerationRequest(
                request_id=f"long-{i}",
                prompt_ids=long_prompt(),
                sampling=SamplingParams(max_tokens=max_tokens),
            ))
        while leng.has_work:
            leng.step()
        lwall = time.monotonic() - lt0
        lres = [leng.poll(f"long-{i}") for i in range(n_long)]
        bad = [r for r in lres if r is None or r.finish_reason == "error"]
        assert not bad, f"{len(bad)}/{n_long} long requests failed: {bad[:2]}"
        long_p50_ms, long_p99_ms = ttft_pcts(lres)
        log(f"long prompts ({long_len} tok x {n_long}): p50 TTFT "
            f"{long_p50_ms:.1f} ms, p99 {long_p99_ms:.1f} ms, "
            f"drained in {lwall:.2f}s")

        # Per-chip-equivalent long leg (the SLO's v5e-8 spread over 8).
        n_lpc = max(1, n_long // 8)
        for i in range(n_lpc):
            leng.submit(GenerationRequest(
                request_id=f"lpc-{i}", prompt_ids=long_prompt(),
                sampling=SamplingParams(max_tokens=max_tokens)))
        while leng.has_work:
            leng.step()
        lpcres = [leng.poll(f"lpc-{i}") for i in range(n_lpc)]
        assert all(r is not None and r.finish_reason != "error"
                   for r in lpcres)
        long_perchip_p50_ms = float(np.percentile(
            np.array(sorted(r.ttft_s for r in lpcres)), 50)) * 1e3
        log(f"long per-chip-equivalent ({n_lpc} concurrent): p50 TTFT "
            f"{long_perchip_p50_ms:.1f} ms")

        # Shared-prefix long prompts: the realistic long-diagnosis shape
        # (shared evidence prefix + per-query tail) through the chunked
        # admission path with prefix reuse.
        shared_long = long_prompt()[: long_len - 256]
        def sl_prompt() -> list[int]:
            return shared_long + list(rng.integers(
                4, cfg.vocab_size - 4, size=256))
        # Seed the prefix, then warm the suffix-bucket chunked-admission
        # ladder (P=2/4 at the 256 bucket) so nothing compiles in-window.
        leng.generate([sl_prompt()], SamplingParams(max_tokens=4))
        leng.generate([sl_prompt() for _ in range(2)],
                      SamplingParams(max_tokens=16))
        leng.generate([sl_prompt() for _ in range(4)],
                      SamplingParams(max_tokens=16))
        st = time.monotonic()
        for i in range(n_long):
            leng.submit(GenerationRequest(
                request_id=f"sl-{i}", prompt_ids=sl_prompt(),
                sampling=SamplingParams(max_tokens=max_tokens)))
        while leng.has_work:
            leng.step()
        slres = [leng.poll(f"sl-{i}") for i in range(n_long)]
        assert all(r is not None and r.finish_reason != "error"
                   for r in slres)
        long_shared_p50_ms, long_shared_p99_ms = ttft_pcts(slres)
        log(f"shared-prefix long prompts: p50 TTFT "
            f"{long_shared_p50_ms:.1f} ms, p99 {long_shared_p99_ms:.1f} ms, "
            f"drained in {time.monotonic() - st:.2f}s")

        # Per-chip-equivalent shared long prompts: the actual v5e-8
        # long-diagnosis shape — shared evidence prefix, per-chip share of
        # the burst.
        for i in range(n_lpc):
            leng.submit(GenerationRequest(
                request_id=f"slpc-{i}", prompt_ids=sl_prompt(),
                sampling=SamplingParams(max_tokens=max_tokens)))
        while leng.has_work:
            leng.step()
        slpc = [leng.poll(f"slpc-{i}") for i in range(n_lpc)]
        assert all(r is not None and r.finish_reason != "error"
                   for r in slpc)
        long_shared_perchip_p50_ms, _ = ttft_pcts(slpc)
        log(f"shared-prefix long per-chip-equivalent ({n_lpc} concurrent): "
            f"p50 TTFT {long_shared_perchip_p50_ms:.1f} ms")
        del leng
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"long-prompt bench skipped: {exc}")

    # --- speculative-decode leg: prompt-lookup speculation A/B ----------
    # Decode-heavy shape (long generations, moderate concurrency) where
    # weight streaming dominates; speculation turns one verify forward into
    # up to spec_k+1 emitted tokens when the output continues an n-gram
    # from its own context (serving/spec.py).  A/B on identical prompts.
    #
    # Honesty note: random-init weights never quote their context — every
    # workload construction tried (random prompts, prompts embedding the
    # model's own prior greedy continuation, fully periodic prompts)
    # measures acceptance at exactly the 1.0 floor, because a random
    # model's argmax never re-walks an n-gram.  So this leg does NOT claim
    # a speculation speedup; it proves the *adaptive controller's floor
    # costs nothing* (spec-enabled ~= fused throughput), which is the
    # property that makes shipping the feature safe.  spec_k defaults to
    # 0 in the serving config; enable it for real quoting checkpoints.
    spec_tok_s = spec_base_tok_s = spec_tpv = None
    try:
        import dataclasses as _dc

        n_sp = int(os.environ.get("BENCH_SPEC_CONCURRENCY", "32"))
        sp_gen = int(os.environ.get("BENCH_SPEC_MAX_TOKENS", "128"))
        sp_cap = prompt_len + sp_gen + 16
        sp_base = EngineConfig(
            max_slots=32,
            num_blocks=min(1400, 32 * ((sp_cap + 15) // 16) + 64),
            block_size=16,
            max_blocks_per_seq=(sp_cap + 15) // 16,
            prefill_buckets=(bucket,),
            max_prefills_per_step=8,
            max_admission_rounds=4,
            decode_steps_per_iter=8,
        )
        sp_prompts = [prompt() for _ in range(n_sp)]
        for spec_on in (False, True):
            se = InferenceEngine(
                cfg, params,
                _dc.replace(sp_base, spec_k=4 if spec_on else 0),
                eos_id=-1)
            # Warm BOTH decode programs: with spec on, the first warmup
            # dispatch is speculative and emits only a few tokens, so an
            # 8-token warmup never compiles the fused K=8 program and its
            # multi-second (cache-)compile would land inside the measured
            # window (observed as a phantom 2-6x "regression").  Warmup
            # prompts are DISTINCT (an identical batch would trip the
            # cold-burst dedup and admit P=1, leaving the P=8 dense
            # program cold) and disjoint from the measured burst (so the
            # burst itself runs all-miss dense rounds).  The second call
            # re-sends one registered prompt to warm the P=8 *chunked*
            # hit-path admission.
            warm_prompts = [prompt() for _ in range(8)]
            se.generate(warm_prompts, SamplingParams(max_tokens=24))
            se.generate([warm_prompts[0]] * 8, SamplingParams(max_tokens=24))
            spt0 = time.monotonic()
            for i, p in enumerate(sp_prompts):
                se.submit(GenerationRequest(
                    request_id=f"sp-{i}", prompt_ids=p,
                    sampling=SamplingParams(max_tokens=sp_gen)))
            while se.has_work:
                se.step()
            dt = time.monotonic() - spt0
            spres = [se.poll(f"sp-{i}") for i in range(n_sp)]
            assert all(r is not None and r.finish_reason != "error"
                       for r in spres)
            tput = sum(len(r.token_ids) for r in spres) / dt
            if spec_on:
                spec_tok_s = tput
                # Per-lane acceptance: emitted tokens per (lane x verify
                # round); 1.0 = no draft ever accepted, k+1 = all accepted.
                spec_tpv = (se.spec_tokens / se.spec_lane_rounds
                            if se.spec_lane_rounds else 0.0)
                log(f"spec decode (k=4): {tput:.0f} tok/s, "
                    f"{spec_tpv:.2f} accepted tokens/lane-round "
                    f"(baseline {spec_base_tok_s:.0f} tok/s, "
                    f"{tput / spec_base_tok_s:.2f}x)")
            else:
                spec_base_tok_s = tput
            del se

    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"spec-decode leg skipped: {exc}")

    # --- spec quote mode: acceptance measured on a model that QUOTES ----
    # Every prompt construction against random-init weights measures the
    # 1.0 floor (tried: random prompts, prompts embedding the model's own
    # prior greedy continuation P+G+P+G[:16], fully periodic prompts, and
    # fixed-point iteration Q <- greedy(P+Q) — the greedy map is chaotic
    # and never converges), so the old self-quote construction was
    # structurally flat: it could only ever print 1.0.  This leg instead
    # builds a checkpoint that genuinely quotes: attention and MLP output
    # projections zeroed (the residual stream carries exactly the current
    # token's embedding) and the unembed wired to a vocab-cycle
    # permutation of the embedding table, so greedy decode
    # deterministically walks the cycle.  A prompt holding two periods of
    # that cycle IS a quoting workload — the true continuation re-walks
    # trigrams the history already contains, the regime the n-gram
    # proposer (serving/spec.py) exists for.  Same engine, same verify
    # kernels, real forward passes; only the checkpoint is synthetic.
    spec_quote_accept = None
    spec_quote_tok_s = spec_quote_base_tok_s = None
    try:
        import copy as _copy

        import jax.numpy as jnp
        from k8s_llm_monitor_tpu.models.config import ModelConfig as _MC

        qcfg = _MC(name="quote-tiny", vocab_size=512, hidden_size=64,
                   intermediate_size=128, num_layers=2, num_heads=4,
                   num_kv_heads=2, dtype="float32", rope_theta=10_000.0)
        qparams = _copy.deepcopy(llama.init_params(jax.random.PRNGKey(11),
                                                   qcfg))
        cyc0, cycn = 10, 48
        orbit = list(range(cyc0, cyc0 + cycn))
        qE = np.asarray(qparams["embed"]["weight"], np.float32)
        qU = np.zeros((qcfg.hidden_size, qcfg.vocab_size), np.float32)
        for qi, qt in enumerate(orbit):
            qU[:, orbit[(qi + 1) % cycn]] = qE[qt]
        for qlayer in qparams["layers"]:
            qlayer["o"]["kernel"] = jnp.zeros_like(qlayer["o"]["kernel"])
            qlayer["down"]["kernel"] = jnp.zeros_like(
                qlayer["down"]["kernel"])
        qparams["lm_head"]["kernel"] = jnp.asarray(qU)

        q_gen, q_n = 96, 8
        # Distinct per-lane prompts (cycle rotations — each still quotes):
        # identical prompts would trip cold-burst dedup and prefix reuse.
        q_prompts = [orbit[qi:] + orbit[:qi] + orbit[qi:] + orbit[:qi]
                     for qi in range(q_n)]
        q_cap = 2 * cycn + q_gen + 1
        q_ecfg = EngineConfig(
            max_slots=q_n, num_blocks=q_n * ((q_cap + 15) // 16) + 8,
            block_size=16, max_blocks_per_seq=(q_cap + 15) // 16,
            prefill_buckets=(2 * cycn,), max_prefills_per_step=q_n,
            decode_steps_per_iter=8, prefix_cache_entries=0)
        import dataclasses as _dc

        for q_k in (0, 4):
            qe = InferenceEngine(
                qcfg, qparams,
                _dc.replace(q_ecfg, spec_k=q_k, spec_min_accept=0.0),
                eos_id=-1)
            qe.generate(q_prompts, SamplingParams(max_tokens=8))  # warm
            qe.spec_tokens = qe.spec_verify_steps = qe.spec_lane_rounds = 0
            qt0 = time.monotonic()
            for qi, qp in enumerate(q_prompts):
                qe.submit(GenerationRequest(
                    request_id=f"q-{qi}", prompt_ids=qp,
                    sampling=SamplingParams(max_tokens=q_gen)))
            while qe.has_work:
                qe.step()
            q_dt = time.monotonic() - qt0
            q_res = [qe.poll(f"q-{qi}") for qi in range(q_n)]
            assert all(r is not None and r.finish_reason != "error"
                       for r in q_res)
            # Self-consistency gate: every lane must have emitted its own
            # cycle continuation exactly, or the acceptance number is
            # measuring a broken construction rather than quoting.
            for qi, r in enumerate(q_res):
                want = [orbit[(qi + j) % cycn] for j in range(q_gen)]
                assert r.token_ids == want, f"lane {qi} left the cycle"
            tput = q_n * q_gen / q_dt
            if q_k:
                spec_quote_tok_s = tput
                spec_quote_accept = (qe.spec_tokens /
                                     max(qe.spec_lane_rounds, 1))
            else:
                spec_quote_base_tok_s = tput
            del qe
        log(f"spec quote mode (cycle checkpoint): {spec_quote_accept:.2f} "
            f"accepted tokens/lane-round (ceiling {4 + 1}.0), "
            f"{spec_quote_tok_s:.0f} tok/s vs {spec_quote_base_tok_s:.0f} "
            f"unspeculated "
            f"({spec_quote_tok_s / spec_quote_base_tok_s:.2f}x)")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"spec quote-mode leg skipped: {exc}")

    # --- long-context verify: the Pallas multi-query kernel on a measured
    # path.  At >= 2048-token tables (the VERIFY_KERNEL_MIN_TABLE_TOKENS
    # gate) the engine selects paged_verify_attention_pallas for spec
    # verify; this leg runs BOTH impls on the same long-context spec
    # workload so the artifact re-measures the kernel-vs-gather crossover
    # every round instead of shipping a stale gate. -------------------
    vk_tok_s = vg_tok_s = None
    try:
        import dataclasses as _dc

        from k8s_llm_monitor_tpu.ops import attention as _attn

        vcfg_e = EngineConfig(
            max_slots=8, num_blocks=8 * 128 + 32, block_size=16,
            max_blocks_per_seq=128,              # 2048-token tables
            prefill_buckets=(512,), max_prefills_per_step=4,
            max_admission_rounds=4, decode_steps_per_iter=8,
            spec_k=4, spec_rounds_per_iter=4,
            spec_min_accept=0.0,                 # always speculate: the
            # leg measures the verify IMPL, not acceptance (floor = 1.0)
        )
        vlen, vgen, nv = 1700, 48, 8

        def vprompt() -> list[int]:
            return list(rng.integers(4, cfg.vocab_size - 4, size=vlen))

        saved_gate = _attn.VERIFY_KERNEL_MIN_TABLE_TOKENS
        for force_gather in (False, True):
            # The gate is a module constant consulted at engine build;
            # raising it beyond the table size forces the gather impl for
            # the A/B.  Restored in finally.
            _attn.VERIFY_KERNEL_MIN_TABLE_TOKENS = (
                10 ** 9 if force_gather else saved_gate)
            try:
                ve = InferenceEngine(cfg, params, vcfg_e, eos_id=-1)
                if (not force_gather and dev.platform == "tpu"):
                    from k8s_llm_monitor_tpu.ops.pallas_attention import (
                        paged_verify_attention_pallas,
                    )
                    assert ve._verify_impl is paged_verify_attention_pallas
                for w in (1, 2, 4):
                    ve.generate([vprompt() for _ in range(w)],
                                SamplingParams(max_tokens=16))
                vt0 = time.monotonic()
                for i in range(nv):
                    ve.submit(GenerationRequest(
                        request_id=f"vk-{i}", prompt_ids=vprompt(),
                        sampling=SamplingParams(max_tokens=vgen)))
                while ve.has_work:
                    ve.step()
                vdt = time.monotonic() - vt0
                vres = [ve.poll(f"vk-{i}") for i in range(nv)]
                assert all(r is not None and r.finish_reason != "error"
                           for r in vres)
                tput = sum(len(r.token_ids) for r in vres) / vdt
                if force_gather:
                    vg_tok_s = tput
                else:
                    vk_tok_s = tput
                del ve
            finally:
                _attn.VERIFY_KERNEL_MIN_TABLE_TOKENS = saved_gate
        log(f"long-context spec verify ({vlen}-token ctx, 2048-token "
            f"tables): Pallas kernel {vk_tok_s:.0f} tok/s vs XLA gather "
            f"{vg_tok_s:.0f} tok/s")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"long-context verify leg skipped: {exc}")

    # --- constrained-decode leg: grammar FSM masking A/B ----------------
    # The diagnosis engine's verdict grammar (diagnosis/grammar.py) masks
    # logits against a token FSM inside the same fused decode scan the
    # free path runs.  This leg measures the per-token decode tax of that
    # mask on one engine serving both kinds of lanes, checks the 100%
    # schema-validity property on everything sampled, and asserts the
    # overhead stays under 10% — the budget that makes constrained
    # verdicts the default for /api/v1/analyze.
    free_ms_tok = constrained_ms_tok = constrained_penalty = None
    try:
        from k8s_llm_monitor_tpu.diagnosis.grammar import (
            parse_verdict,
            verdict_fsm,
        )

        if cfg.vocab_size < 259:
            raise ValueError(
                f"vocab {cfg.vocab_size} < byte-tokenizer vocab 259")
        g_n = int(os.environ.get("BENCH_CONSTRAINED_CONCURRENCY", "8"))
        g_len, g_gen = 64, 256
        fsm = verdict_fsm(eos_id=2)
        g_cap = g_len + max(g_gen, fsm.max_len) + 16
        g_ecfg = EngineConfig(
            max_slots=g_n,
            num_blocks=g_n * ((g_cap + 15) // 16) + 16,
            block_size=16,
            max_blocks_per_seq=(g_cap + 15) // 16,
            prefill_buckets=(g_len,),
            max_prefills_per_step=g_n,
            decode_steps_per_iter=8,
        )
        ge = InferenceEngine(cfg, params, g_ecfg, eos_id=2)
        ge.set_grammar(fsm)

        def g_prompt() -> list[int]:
            return [int(t) for t in
                    rng.integers(4, min(cfg.vocab_size, 259) - 4, size=g_len)]

        g_free = SamplingParams(max_tokens=g_gen, temperature=0.7)
        g_con = SamplingParams(max_tokens=1, temperature=0.7,
                               constrained=True)
        # Warm both program families (free and constrained decode).
        ge.generate([g_prompt() for _ in range(g_n)],
                    SamplingParams(max_tokens=8, temperature=0.7))
        ge.generate([g_prompt() for _ in range(g_n)], g_con)

        def per_token_ms(results) -> float:
            rates = [(r.latency_s - r.ttft_s) * 1e3 / (len(r.token_ids) - 1)
                     for r in results if len(r.token_ids) > 1]
            return float(np.median(rates))

        free_res = ge.generate([g_prompt() for _ in range(g_n)], g_free)
        con_res = ge.generate([g_prompt() for _ in range(g_n)], g_con)
        assert all(r.finish_reason != "error" for r in free_res + con_res)
        for r in con_res:  # the 100% schema-validity property, re-proven
            parse_verdict("".join(chr(t - 3) for t in r.token_ids
                                  if 3 <= t < 259))
        free_ms_tok = per_token_ms(free_res)
        constrained_ms_tok = per_token_ms(con_res)
        constrained_penalty = (constrained_ms_tok - free_ms_tok) \
            / free_ms_tok
        log(f"constrained decode: {constrained_ms_tok:.2f} ms/tok vs "
            f"free {free_ms_tok:.2f} ms/tok "
            f"({constrained_penalty * 100:+.1f}% tok/s penalty)")
        assert constrained_penalty < 0.10, (
            f"constrained decode tax {constrained_penalty * 100:.1f}% "
            f"exceeds the 10% budget")
        del ge
    except AssertionError:
        raise  # a blown overhead budget IS a bench failure
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"constrained-decode leg skipped: {exc}")

    # BASELINE config #3: encoder embedding throughput (BGE-large geometry
    # on TPU, tiny on CPU smoke runs), via the anomaly detector's batch path.
    embed_docs_per_s = 0.0
    try:
        from k8s_llm_monitor_tpu.analysis.anomaly import EmbeddingAnomalyDetector
        from k8s_llm_monitor_tpu.models.config import ENCODER_PRESETS

        enc_name = os.environ.get(
            "BENCH_ENCODER",
            "bge-large-bf16" if dev.platform == "tpu" else "tiny-encoder")
        det = EmbeddingAnomalyDetector(ENCODER_PRESETS[enc_name])
        docs = [f"Warning: BackOff restarting failed container web-{i} "
                f"in pod default/web-{i}; exit code 137 OOMKilled" * 3
                for i in range(64)]
        det.embed(docs)  # compile
        et0 = time.monotonic()
        reps = 5
        for _ in range(reps):
            emb = det.embed(docs)
        embed_wall = time.monotonic() - et0
        embed_docs_per_s = reps * len(docs) / embed_wall
        log(f"encoder {enc_name}: {embed_docs_per_s:.0f} docs/s "
            f"({len(docs)}-doc batches)")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"encoder bench skipped: {exc}")

    # BASELINE config #1: ONE /api/v1/query root-cause request end-to-end
    # through the booted HTTP server (fake cluster, template backend — the
    # zero-accelerator CPU path), timed as a real HTTP round trip including
    # evidence collection.  The reference documents this endpoint but never
    # implemented it (README.md:89-95 vs cmd/server/main.go:97-141), so
    # this is the number it has no counterpart for.
    query_e2e_ms = None
    try:
        import urllib.request

        from k8s_llm_monitor_tpu.monitor.analysis import (
            AnalysisEngine,
            TemplateBackend,
        )
        from k8s_llm_monitor_tpu.monitor.client import Client
        from k8s_llm_monitor_tpu.monitor.cluster import (
            FakeCluster,
            seed_demo_cluster,
        )
        from k8s_llm_monitor_tpu.monitor.config import Config, MetricsConfig
        from k8s_llm_monitor_tpu.monitor.manager import Manager
        from k8s_llm_monitor_tpu.monitor.server import MonitorServer

        fake = seed_demo_cluster(FakeCluster())
        qclient = Client(fake, namespaces=["default", "kube-system"])
        qmanager = Manager(
            qclient, MetricsConfig(namespaces=["default"],
                                   enable_network=True))
        qmanager.collect()
        qanalysis = AnalysisEngine(
            TemplateBackend(), client=qclient, manager=qmanager)
        srv = MonitorServer(config=Config(), client=qclient,
                            manager=qmanager, analysis=qanalysis, port=0)
        srv.start()
        qreq = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/api/v1/query",
            data=json.dumps(
                {"question": "why is the web pod failing to reach the "
                             "database service?"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(qreq) as r:  # warm the route once
            r.read()
        qtimes = []
        for _ in range(5):
            qt0 = time.monotonic()
            with urllib.request.urlopen(qreq) as r:
                r.read()
            qtimes.append(time.monotonic() - qt0)
        query_e2e_ms = float(np.median(qtimes)) * 1e3
        srv.stop()
        log(f"query E2E (HTTP round trip, fake cluster, template backend): "
            f"{query_e2e_ms:.1f} ms")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"query E2E leg skipped: {exc}")

    # --- warm-restart leg: crash-safe lifecycle handover cost (PR 4).
    # Kills the step loop under streaming load and measures death-detected
    # -> first replayed token reaching a caller: supervisor teardown +
    # engine rebuild via the factory + journal-trimmed re-admission
    # (docs/resilience.md).  Small dedicated engine; a pre-kill warm build
    # on the same shapes keeps jit compiles out of the measured window.
    restart_to_token_ms = restart_replayed = None
    try:
        import tempfile
        import threading as _th

        from k8s_llm_monitor_tpu.resilience.faults import get_injector
        from k8s_llm_monitor_tpu.resilience.journal import RequestJournal
        from k8s_llm_monitor_tpu.resilience.retry import Backoff
        from k8s_llm_monitor_tpu.serving.supervisor import EngineSupervisor

        r_len, r_gen, r_n = 64, 96, 4
        r_cap = r_len + r_gen + 16
        r_ecfg = EngineConfig(
            max_slots=r_n,
            num_blocks=r_n * ((r_cap + 15) // 16) + 16,
            block_size=16,
            max_blocks_per_seq=(r_cap + 15) // 16,
            prefill_buckets=(r_len,),
            max_prefills_per_step=r_n,
            decode_steps_per_iter=4,
        )

        def r_factory():
            return InferenceEngine(cfg, params, r_ecfg, eos_id=-1)

        def r_prompt() -> list[int]:
            return [int(t) for t in
                    rng.integers(4, cfg.vocab_size - 4, size=r_len)]

        warm_eng = r_factory()
        warm_eng.generate([r_prompt() for _ in range(r_n)],
                          SamplingParams(max_tokens=4))
        del warm_eng

        sup = EngineSupervisor(
            r_factory,
            journal=RequestJournal(tempfile.mkdtemp(prefix="bench-wal-"),
                                   fsync="never"),
            max_restarts=2,
            backoff=Backoff(base_s=0.05, cap_s=0.1, jitter=0.0),
            heartbeat_timeout_s=600.0,   # death-signal path only, no wedge
            poll_interval_s=0.01,
        )
        try:
            stamps: list[list[float]] = [[] for _ in range(r_n)]
            handles = [sup.submit(r_prompt(),
                                  SamplingParams(max_tokens=r_gen))
                       for _ in range(r_n)]

            def r_consume(i):
                for _tok in handles[i].stream(timeout=120.0):
                    stamps[i].append(time.monotonic())

            r_threads = [_th.Thread(target=r_consume, args=(i,), daemon=True)
                         for i in range(r_n)]
            for t in r_threads:
                t.start()
            # Every request must have streamed progress before the kill so
            # the replay actually trims delivered tokens.
            r_deadline = time.monotonic() + 120.0
            while min((len(s) for s in stamps), default=0) < 4:
                if time.monotonic() > r_deadline:
                    raise TimeoutError("no streaming progress before kill")
                time.sleep(0.002)
            get_injector().arm("step_loop_crash", rate=1.0, times=1)
            while sup.state == "serving":
                if time.monotonic() > r_deadline:
                    raise TimeoutError("injected crash never detected")
                time.sleep(0.0005)
            t_dead = time.monotonic()
            for t in r_threads:
                t.join(timeout=120.0)
            r_res = [h.result(timeout=120.0) for h in handles]
            assert all(r.finish_reason != "error" for r in r_res)
            assert all(len(r.token_ids) == r_gen for r in r_res), \
                "lost or duplicated tokens across the restart"
            # Any token stamped after death detection is from the rebuilt
            # engine (the supervisor severs the old loop's observer).
            first_after = min(t for s in stamps for t in s if t > t_dead)
            restart_to_token_ms = (first_after - t_dead) * 1e3
            restart_replayed = sup.replayed_total
            log(f"warm restart: {restart_to_token_ms:.0f} ms from step-loop "
                f"death to first replayed token ({sup.restarts} restart, "
                f"{restart_replayed} requests replayed)")
        finally:
            sup.shutdown(grace_s=5.0)
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"warm-restart leg skipped: {exc}")

    fleet_stats: dict = {}
    try:
        if os.environ.get("BENCH_FLEET", "1") == "1":
            fleet_stats = fleet_leg(cfg, params)
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"fleet leg skipped: {exc}")

    kv_tier_stats_d: dict = {}
    try:
        if os.environ.get("BENCH_KVTIER", "1") == "1":
            kv_tier_stats_d = kv_tier_leg(cfg, params)
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"kv tier leg skipped: {exc}")

    migration_stats: dict = {}
    try:
        if os.environ.get("BENCH_MIGRATION", "1") == "1":
            migration_stats = migration_leg(cfg, params)
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"prefix migration leg skipped: {exc}")

    tracing_stats: dict = {}
    try:
        if os.environ.get("BENCH_TRACING", "1") == "1":
            tracing_stats = tracing_leg(cfg, params)
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"tracing overhead leg skipped: {exc}")

    signals_stats: dict = {}
    try:
        if os.environ.get("BENCH_SIGNALS", "1") == "1":
            signals_stats = signals_leg(cfg, params)
    except AssertionError:
        raise  # a blown scraper budget IS a bench failure
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"signals overhead leg skipped: {exc}")

    elastic_stats: dict = {}
    try:
        if os.environ.get("BENCH_ELASTIC", "1") == "1":
            elastic_stats = elasticity_leg(cfg, params)
    except AssertionError:
        raise  # a blown handoff-TTFT budget IS a bench failure
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"elasticity leg skipped: {exc}")

    tenant_stats: dict = {}
    try:
        if os.environ.get("BENCH_TENANT", "1") == "1":
            tenant_stats = tenant_fairness_leg(cfg, params)
    except AssertionError:
        raise  # a blown fairness/exactness gate IS a bench failure
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"tenant fairness leg skipped: {exc}")

    extras = {
        "model": model_name,
        "quant": quant,
        "concurrency": n_requests,
        "prompt_len": prompt_len,
        "max_tokens": max_tokens,
        "p99_ttft_ms": round(p99 * 1e3, 2),
        "throughput_tok_s": round(toks_per_s, 1),
        "wall_s": round(wall, 2),
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "warmup_s": round(warmup_s, 1),
        "compile_cache_warm": cache_was_warm,
        "weight_gib": round(weight_bytes / 2**30, 2),
        "embed_docs_per_s": round(embed_docs_per_s, 1),
        "slo_context": "500ms SLO is v5e-8 (8 chips); this is 1 chip at "
                       "8x the SLO's per-chip load",
        # Tail budget: with a uniform-length burst admitted FIFO, p99 TTFT
        # ~= the serial prefill time of the whole burst on this one chip
        # (admission-order physics, not queue mismanagement); an 8-chip
        # deployment divides it by the chip count.
        "tail_budget": "p99 ~= burst_prefill_total / n_chips",
    }
    if query_e2e_ms is not None:
        extras["query_e2e_ms"] = round(query_e2e_ms, 2)
    if perchip_p50_ms is not None:
        # Informational only: burst/8 through one chip models neither the
        # ICI collectives nor the shared-KV-pool batching of a real slice.
        # The measured multi-chip numbers are the mesh_* keys below.
        extras["perchip_equiv_p50_ttft_ms"] = round(perchip_p50_ms, 2)
        extras["perchip_equiv_p99_ttft_ms"] = round(perchip_p99_ms, 2)
        extras["perchip_equiv_informational"] = True
    extras.update(mesh_stats)
    if shared_p50_ms is not None:
        extras["shared_prefix_p50_ttft_ms"] = round(shared_p50_ms, 2)
        extras["shared_prefix_p99_ttft_ms"] = round(shared_p99_ms, 2)
        extras["shared_prefix_len"] = shared_len
    if slo_class_stats is not None:
        # Per-class TTFT under the mixed-class burst; the interactive
        # entry carries the p99 <= 2x p50 tail verdict (tail_ok).
        extras["slo_class_burst"] = slo_class_stats
    if prefill_tflops:
        extras["prefill_tflops"] = round(prefill_tflops, 1)
        extras["prefill_mfu"] = round(prefill_mfu, 3)
    if decode_gbs:
        extras["decode_weight_gbs"] = round(decode_gbs, 1)
        extras["decode_bw_util"] = round(decode_bw_util, 3)
        if decode_step_ms is not None:
            extras["decode_step_ms"] = round(decode_step_ms, 2)
            extras["decode_step_stream_ms"] = round(decode_stream_ms, 2)
            extras["decode_step_matmul_ms"] = round(decode_matmul_ms, 2)
            extras["decode_attribution"] = (
                "compute/bandwidth ridge at this lane count: weight "
                "streaming + B-scaled matmul each ~10ms; not HBM-bound")
    extras["decode_path"] = decode_path
    if fused_decode_step_ms is not None:
        extras["fused_decode_step_ms"] = round(fused_decode_step_ms, 2)
        extras["fallback_decode_step_ms"] = round(fallback_decode_step_ms, 2)
        extras["fused_matches_fallback"] = fused_match
    if decode_phases is not None:
        extras["decode_attn_ms"] = round(decode_phases["decode_attn_ms"], 2)
        extras["decode_sample_ms"] = round(
            decode_phases["decode_sample_ms"], 2)
        extras["decode_host_gap_ms"] = round(decode_host_gap_ms, 2)
    if dec_e2e_tok_s is not None:
        extras["decode_e2e_128lane_tok_s"] = round(dec_e2e_tok_s, 1)
    if w8a8_decode_tok_s is not None:
        extras["w8a8_decode_tok_s"] = round(w8a8_decode_tok_s, 1)
    if long_p50_ms is not None:  # 0.0 would read as a perfect score
        extras["long_prompt_p50_ttft_ms"] = round(long_p50_ms, 2)
        extras["long_prompt_p99_ttft_ms"] = round(long_p99_ms, 2)
        extras["long_quant"] = "w8a8" if quant == "int8" else quant
    if long_shared_p50_ms is not None:
        extras["long_shared_prefix_p50_ttft_ms"] = round(long_shared_p50_ms, 2)
        extras["long_shared_prefix_p99_ttft_ms"] = round(
            long_shared_p99_ms, 2)
    if long_shared_perchip_p50_ms is not None:
        extras["long_shared_perchip_p50_ttft_ms"] = round(
            long_shared_perchip_p50_ms, 2)
    if long_perchip_p50_ms is not None:
        extras["long_perchip_equiv_p50_ttft_ms"] = round(long_perchip_p50_ms, 2)
    if w8a8_p50_ms is not None:
        extras["w8a8_p50_ttft_ms"] = round(w8a8_p50_ms, 2)
        extras["w8a8_p99_ttft_ms"] = round(w8a8_p99_ms, 2)
        extras["w8a8_wall_s"] = round(w8a8_wall, 2)
    if w8a8_perchip_p50_ms is not None:
        extras["w8a8_perchip_p50_ttft_ms"] = round(w8a8_perchip_p50_ms, 2)
        extras["w8a8_perchip_p99_ttft_ms"] = round(w8a8_perchip_p99_ms, 2)
    if w8a8_shared_p50_ms is not None:
        extras["w8a8_shared_prefix_p50_ttft_ms"] = round(w8a8_shared_p50_ms, 2)
        extras["w8a8_shared_prefix_p99_ttft_ms"] = round(
            w8a8_shared_p99_ms, 2)
    if cold_shared_p50_ms is not None:
        extras["w8a8_cold_shared_prefix_p50_ttft_ms"] = round(
            cold_shared_p50_ms, 2)
        extras["w8a8_cold_shared_prefix_p99_ttft_ms"] = round(
            cold_shared_p99_ms, 2)
    if spec_tok_s is not None:
        extras["spec_decode_tok_s"] = round(spec_tok_s, 1)
        extras["spec_baseline_tok_s"] = round(spec_base_tok_s, 1)
        extras["spec_accept_per_lane_round"] = round(spec_tpv, 2)
        extras["spec_default"] = "off (spec_k=0): random-init weights "\
            "measure the 1.0 acceptance floor on every construction; "\
            "this leg proves the adaptive floor costs ~nothing"
    if spec_quote_accept is not None:
        extras["spec_quote_accept"] = round(spec_quote_accept, 2)
        extras["spec_quote_tok_s"] = round(spec_quote_tok_s, 1)
        extras["spec_quote_base_tok_s"] = round(spec_quote_base_tok_s, 1)
        extras["spec_quote_speedup"] = round(
            spec_quote_tok_s / max(spec_quote_base_tok_s, 1e-9), 2)
    if vk_tok_s is not None and vg_tok_s is not None:
        extras["verify_kernel_longctx_tok_s"] = round(vk_tok_s, 1)
        extras["verify_gather_longctx_tok_s"] = round(vg_tok_s, 1)
    if constrained_penalty is not None:
        extras["constrained_decode_ms_per_tok"] = round(constrained_ms_tok, 3)
        extras["free_decode_ms_per_tok"] = round(free_ms_tok, 3)
        extras["constrained_decode_penalty"] = round(constrained_penalty, 3)
    if restart_to_token_ms is not None:
        extras["warm_restart_to_token_ms"] = round(restart_to_token_ms, 1)
        extras["warm_restart_replayed"] = restart_replayed
    extras.update(fleet_stats)
    extras.update(kv_tier_stats_d)
    extras.update(migration_stats)
    extras.update(tracing_stats)
    extras.update(signals_stats)
    extras.update(elastic_stats)
    extras.update(tenant_stats)
    log(f"total bench time {time.monotonic() - t0:.0f}s")
    print(json.dumps({
        "metric": "p50_ttft_100c_ms",
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(0.5 / p50, 3) if p50 > 0 else 0.0,
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
