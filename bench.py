#!/usr/bin/env python
"""Single-chip serving benchmark — the north-star SLO tracker.

Measures p50 TTFT for a burst of concurrent diagnosis-sized queries through
the continuous-batching engine (BASELINE.md config #4, scaled to the one
available chip), plus decode throughput, and prints ONE JSON line:

    {"metric": "p50_ttft_100c_ms", "value": <ms>, "unit": "ms",
     "vs_baseline": <500ms / p50>, ...}

``vs_baseline`` is measured against the north-star SLO (p50 TTFT < 500 ms,
BASELINE.md / BASELINE.json north_star) since the reference publishes no
benchmark numbers of its own (verified in SURVEY.md §6): > 1.0 beats the SLO.

Model: LLAMA_1B preset (models/config.py) with random-init bf16 weights —
the per-chip arithmetic matches the 8B-on-v5e-8 target within a small factor
and leaves HBM headroom for the KV pool on a 16 GB chip.

Run: ``python bench.py`` (uses the default JAX platform — the real TPU under
the driver; set BENCH_CONCURRENCY / BENCH_MODEL / JAX_PLATFORMS=cpu to
shrink for local smoke runs).
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    t0 = time.monotonic()
    import numpy as np
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import PRESETS
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        GenerationRequest,
        InferenceEngine,
        SamplingParams,
    )

    model_name = os.environ.get("BENCH_MODEL", "llama-1b")
    n_requests = int(os.environ.get("BENCH_CONCURRENCY", "100"))
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN", "192"))
    max_tokens = int(os.environ.get("BENCH_MAX_TOKENS", "48"))

    cfg = PRESETS[model_name]
    dev = jax.devices()[0]
    log(f"bench: {model_name} on {dev.platform}:{dev.device_kind} "
        f"({n_requests} concurrent, prompt {prompt_len}, gen {max_tokens})")

    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    ecfg = EngineConfig(
        max_slots=128,
        num_blocks=4096,
        block_size=16,
        max_blocks_per_seq=32,
        prefill_buckets=(256,),
        max_prefills_per_step=16,
        max_admission_rounds=8,
        decode_steps_per_iter=8,
    )
    eng = InferenceEngine(cfg, params, ecfg, eos_id=-1)

    rng = np.random.default_rng(0)

    def prompt() -> list[int]:
        return list(rng.integers(4, cfg.vocab_size - 4, size=prompt_len))

    # Warm up every compiled shape — batched (P=16) and single (P=1) prefill,
    # and the fused-decode K ladder (8, 4, 2, 1) the drain will walk — so the
    # measured run excludes compile time.
    log("warmup (compiles prefill/decode)...")
    wt0 = time.monotonic()
    eng.generate([prompt() for _ in range(2)],
                 SamplingParams(max_tokens=max_tokens))
    eng.generate([prompt()], SamplingParams(max_tokens=4))
    log(f"warmup done in {time.monotonic() - wt0:.1f}s")

    # --- concurrent burst: all requests queued at t=0, engine drains ---
    bt0 = time.monotonic()
    for i in range(n_requests):
        eng.submit(GenerationRequest(
            request_id=f"bench-{i}",
            prompt_ids=prompt(),
            sampling=SamplingParams(max_tokens=max_tokens),
        ))
    steps0, prefills0 = eng.steps, eng.prefills
    while eng.has_work:
        eng.step()
    wall = time.monotonic() - bt0

    results = [eng.poll(f"bench-{i}") for i in range(n_requests)]
    assert all(r is not None and r.finish_reason != "error" for r in results)
    ttfts = np.array(sorted(r.ttft_s for r in results))
    total_tokens = sum(len(r.token_ids) for r in results)
    p50 = float(np.percentile(ttfts, 50))
    p99 = float(np.percentile(ttfts, 99))
    toks_per_s = total_tokens / wall

    log(f"drained {n_requests} requests in {wall:.2f}s "
        f"({eng.steps - steps0} steps, {eng.prefills - prefills0} prefills, "
        f"{eng.preemptions} preemptions)")
    log(f"p50 TTFT {p50 * 1e3:.1f} ms | p99 {p99 * 1e3:.1f} ms | "
        f"throughput {toks_per_s:.0f} tok/s | total {time.monotonic()-t0:.0f}s")

    # BASELINE config #3: encoder embedding throughput (BGE-large geometry
    # on TPU, tiny on CPU smoke runs), via the anomaly detector's batch path.
    embed_docs_per_s = 0.0
    try:
        from k8s_llm_monitor_tpu.analysis.anomaly import EmbeddingAnomalyDetector
        from k8s_llm_monitor_tpu.models.config import ENCODER_PRESETS

        enc_name = os.environ.get(
            "BENCH_ENCODER",
            "bge-large" if dev.platform == "tpu" else "tiny-encoder")
        det = EmbeddingAnomalyDetector(ENCODER_PRESETS[enc_name])
        docs = [f"Warning: BackOff restarting failed container web-{i} "
                f"in pod default/web-{i}; exit code 137 OOMKilled" * 3
                for i in range(64)]
        det.embed(docs)  # compile
        et0 = time.monotonic()
        reps = 5
        for _ in range(reps):
            emb = det.embed(docs)
        embed_wall = time.monotonic() - et0
        embed_docs_per_s = reps * len(docs) / embed_wall
        log(f"encoder {enc_name}: {embed_docs_per_s:.0f} docs/s "
            f"({len(docs)}-doc batches)")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"encoder bench skipped: {exc}")

    print(json.dumps({
        "metric": "p50_ttft_100c_ms",
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(0.5 / p50, 3) if p50 > 0 else 0.0,
        "extras": {
            "model": model_name,
            "concurrency": n_requests,
            "prompt_len": prompt_len,
            "max_tokens": max_tokens,
            "p99_ttft_ms": round(p99 * 1e3, 2),
            "throughput_tok_s": round(toks_per_s, 1),
            "wall_s": round(wall, 2),
            "platform": dev.platform,
            "embed_docs_per_s": round(embed_docs_per_s, 1),
        },
    }))


if __name__ == "__main__":
    main()
