#!/usr/bin/env python
"""Single-chip serving benchmark — the north-star SLO tracker.

Measures p50 TTFT for a burst of concurrent diagnosis-sized queries through
the continuous-batching engine (BASELINE.md config #4, scaled to the one
available chip), plus decode throughput, and prints ONE JSON line:

    {"metric": "p50_ttft_100c_ms", "value": <ms>, "unit": "ms",
     "vs_baseline": <500ms / p50>, ...}

``vs_baseline`` is measured against the north-star SLO (p50 TTFT < 500 ms,
BASELINE.md / BASELINE.json north_star) since the reference publishes no
benchmark numbers of its own (verified in SURVEY.md §6): > 1.0 beats the SLO.

Model: LLAMA_1B preset (models/config.py) with random-init bf16 weights —
the per-chip arithmetic matches the 8B-on-v5e-8 target within a small factor
and leaves HBM headroom for the KV pool on a 16 GB chip.

Run: ``python bench.py`` (uses the default JAX platform — the real TPU under
the driver; set BENCH_CONCURRENCY / BENCH_MODEL / JAX_PLATFORMS=cpu to
shrink for local smoke runs).
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def main() -> None:
    t0 = time.monotonic()
    import numpy as np
    import jax

    from k8s_llm_monitor_tpu.models import llama
    from k8s_llm_monitor_tpu.models.config import PRESETS
    from k8s_llm_monitor_tpu.serving.engine import (
        EngineConfig,
        GenerationRequest,
        InferenceEngine,
        SamplingParams,
    )

    model_name = os.environ.get("BENCH_MODEL", "llama-1b")
    n_requests = int(os.environ.get("BENCH_CONCURRENCY", "100"))
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN", "192"))
    max_tokens = int(os.environ.get("BENCH_MAX_TOKENS", "48"))

    cfg = PRESETS[model_name]
    dev = jax.devices()[0]
    log(f"bench: {model_name} on {dev.platform}:{dev.device_kind} "
        f"({n_requests} concurrent, prompt {prompt_len}, gen {max_tokens})")

    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    ecfg = EngineConfig(
        max_slots=int(os.environ.get("BENCH_SLOTS", "128")),
        num_blocks=4096,
        block_size=16,
        max_blocks_per_seq=32,
        prefill_buckets=(256,),
        max_prefills_per_step=int(os.environ.get("BENCH_PREFILL_BATCH", "32")),
        max_admission_rounds=8,
        decode_steps_per_iter=int(os.environ.get("BENCH_DECODE_STEPS", "8")),
    )
    eng = InferenceEngine(cfg, params, ecfg, eos_id=-1)

    rng = np.random.default_rng(0)

    def prompt() -> list[int]:
        return list(rng.integers(4, cfg.vocab_size - 4, size=prompt_len))

    # Warm up every compiled shape — batched (P=max_prefills_per_step) and
    # single (P=1) prefill, and the fused-decode K ladder the drain will
    # walk — so the measured run excludes compile time.
    log("warmup (compiles prefill/decode)...")
    wt0 = time.monotonic()
    eng.generate([prompt() for _ in range(2)],
                 SamplingParams(max_tokens=max_tokens))
    eng.generate([prompt()], SamplingParams(max_tokens=4))
    log(f"warmup done in {time.monotonic() - wt0:.1f}s")

    # --- concurrent burst: all requests queued at t=0, engine drains ---
    bt0 = time.monotonic()
    for i in range(n_requests):
        eng.submit(GenerationRequest(
            request_id=f"bench-{i}",
            prompt_ids=prompt(),
            sampling=SamplingParams(max_tokens=max_tokens),
        ))
    steps0, prefills0 = eng.steps, eng.prefills
    while eng.has_work:
        eng.step()
    wall = time.monotonic() - bt0

    results = [eng.poll(f"bench-{i}") for i in range(n_requests)]
    assert all(r is not None and r.finish_reason != "error" for r in results)
    steps_run, prefills_run = eng.steps - steps0, eng.prefills - prefills0
    preempts = eng.preemptions
    del eng  # free the headline KV pool before the long-prompt engine
    ttfts = np.array(sorted(r.ttft_s for r in results))
    total_tokens = sum(len(r.token_ids) for r in results)
    p50 = float(np.percentile(ttfts, 50))
    p99 = float(np.percentile(ttfts, 99))
    toks_per_s = total_tokens / wall

    log(f"drained {n_requests} requests in {wall:.2f}s "
        f"({steps_run} steps, {prefills_run} prefills, "
        f"{preempts} preemptions)")
    log(f"p50 TTFT {p50 * 1e3:.1f} ms | p99 {p99 * 1e3:.1f} ms | "
        f"throughput {toks_per_s:.0f} tok/s | total {time.monotonic()-t0:.0f}s")

    # Long-prompt leg: realistic multi-KB diagnosis prompts exercising
    # chunked prefill (prompts > the largest bucket), so the headline number
    # can't hide a slow chunk path.  Separate engine so bucket shapes and the
    # KV pool match the longer sequences.
    long_p50_ms = None  # omitted from the JSON if the leg doesn't complete
    try:
        n_long = int(os.environ.get("BENCH_LONG_CONCURRENCY", "16"))
        long_len = int(os.environ.get("BENCH_LONG_PROMPT_LEN", "1536"))
        lcfg = EngineConfig(
            max_slots=16,
            num_blocks=2048,
            block_size=16,
            max_blocks_per_seq=128,
            prefill_buckets=(512,),
            max_prefills_per_step=4,
            max_admission_rounds=4,
            decode_steps_per_iter=8,
        )
        leng = InferenceEngine(cfg, params, lcfg, eos_id=-1)

        def long_prompt() -> list[int]:
            return list(rng.integers(4, cfg.vocab_size - 4, size=long_len))

        leng.generate([long_prompt()], SamplingParams(max_tokens=16))  # warm
        lt0 = time.monotonic()
        for i in range(n_long):
            leng.submit(GenerationRequest(
                request_id=f"long-{i}",
                prompt_ids=long_prompt(),
                sampling=SamplingParams(max_tokens=max_tokens),
            ))
        while leng.has_work:
            leng.step()
        lwall = time.monotonic() - lt0
        lres = [leng.poll(f"long-{i}") for i in range(n_long)]
        bad = [r for r in lres if r is None or r.finish_reason == "error"]
        assert not bad, f"{len(bad)}/{n_long} long requests failed: {bad[:2]}"
        long_p50_ms = float(np.percentile(
            np.array(sorted(r.ttft_s for r in lres)), 50)) * 1e3
        log(f"long prompts ({long_len} tok x {n_long}): p50 TTFT "
            f"{long_p50_ms:.1f} ms, drained in {lwall:.2f}s")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"long-prompt bench skipped: {exc}")

    # BASELINE config #3: encoder embedding throughput (BGE-large geometry
    # on TPU, tiny on CPU smoke runs), via the anomaly detector's batch path.
    embed_docs_per_s = 0.0
    try:
        from k8s_llm_monitor_tpu.analysis.anomaly import EmbeddingAnomalyDetector
        from k8s_llm_monitor_tpu.models.config import ENCODER_PRESETS

        enc_name = os.environ.get(
            "BENCH_ENCODER",
            "bge-large" if dev.platform == "tpu" else "tiny-encoder")
        det = EmbeddingAnomalyDetector(ENCODER_PRESETS[enc_name])
        docs = [f"Warning: BackOff restarting failed container web-{i} "
                f"in pod default/web-{i}; exit code 137 OOMKilled" * 3
                for i in range(64)]
        det.embed(docs)  # compile
        et0 = time.monotonic()
        reps = 5
        for _ in range(reps):
            emb = det.embed(docs)
        embed_wall = time.monotonic() - et0
        embed_docs_per_s = reps * len(docs) / embed_wall
        log(f"encoder {enc_name}: {embed_docs_per_s:.0f} docs/s "
            f"({len(docs)}-doc batches)")
    except Exception as exc:  # noqa: BLE001 — extras never fail the bench
        log(f"encoder bench skipped: {exc}")

    extras = {
        "model": model_name,
        "concurrency": n_requests,
        "prompt_len": prompt_len,
        "max_tokens": max_tokens,
        "p99_ttft_ms": round(p99 * 1e3, 2),
        "throughput_tok_s": round(toks_per_s, 1),
        "wall_s": round(wall, 2),
        "platform": dev.platform,
        "embed_docs_per_s": round(embed_docs_per_s, 1),
    }
    if long_p50_ms is not None:  # 0.0 would read as a perfect score
        extras["long_prompt_p50_ttft_ms"] = round(long_p50_ms, 2)
    print(json.dumps({
        "metric": "p50_ttft_100c_ms",
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(0.5 / p50, 3) if p50 > 0 else 0.0,
        "extras": extras,
    }))


if __name__ == "__main__":
    main()
